"""Tracing / profiling — phase markers and run metrics.

Reference: ``OpStep`` job-group labels (utils/spark/OpStep.scala:38-46),
``JobGroupUtil.withJobGroup`` (core/.../utils/spark/JobGroupUtil.scala),
``OpSparkListener`` per-stage/app metrics collection
(utils/spark/OpSparkListener.scala:62-148, AppMetrics :173).

TPU redesign: there is no Spark scheduler to listen to — phases are explicit
context managers that accumulate wall-clock into a per-run
``MetricsCollector``, and the deep profile comes from XLA itself via
``jax.profiler`` (trace files viewable in TensorBoard/Perfetto), which
replaces the Spark UI.
"""
from __future__ import annotations

import contextlib
import enum
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

__all__ = ["OpStep", "MetricsCollector", "AppMetrics", "StepMetrics",
           "with_job_group", "current_collector", "install_collector",
           "profile_to", "RunCounters", "COUNTERS", "reset_counters",
           "count_upload", "count_fetch", "count_drain", "count_launch",
           "fetch_timed", "StageProfile", "PlanProfiler"]


class OpStep(enum.Enum):
    """Phases of a workflow run (OpStep.scala:38-46 parity)."""

    CrossValidation = "Cross-validation"
    DataReadingAndFiltering = "Data reading and filtering"
    FeatureEngineering = "Feature engineering"
    ModelIO = "Model loading / saving"
    Other = "Other"
    ResultsSaving = "Results saving"
    Scoring = "Scoring"  # TPU addition: batched/streaming score phases
    Serving = "Serving"  # TPU addition: online micro-batch serving (serving/)


@dataclass
class StepMetrics:
    step: str
    duration_secs: float
    count: int = 1

    def to_json(self) -> Dict[str, Any]:
        return {"step": self.step, "durationSecs": self.duration_secs,
                "count": self.count}


@dataclass
class AppMetrics:
    """Aggregate run metrics (OpSparkListener.AppMetrics parity)."""

    app_name: str = "transmogrifai_tpu"
    run_type: Optional[str] = None
    app_start_time: float = field(default_factory=time.time)
    app_end_time: Optional[float] = None
    step_metrics: Dict[str, StepMetrics] = field(default_factory=dict)
    custom_tags: Dict[str, str] = field(default_factory=dict)

    @property
    def app_duration(self) -> float:
        end = self.app_end_time if self.app_end_time is not None else time.time()
        return end - self.app_start_time

    def to_json(self) -> Dict[str, Any]:
        return {
            "appName": self.app_name,
            "runType": self.run_type,
            "appDurationSecs": self.app_duration,
            "stepMetrics": [m.to_json() for m in self.step_metrics.values()],
            "customTags": dict(self.custom_tags),
        }


class MetricsCollector:
    """Accumulates per-step wall-clock for one run; thread-safe."""

    def __init__(self, app_name: str = "transmogrifai_tpu",
                 run_type: Optional[str] = None):
        self.metrics = AppMetrics(app_name=app_name, run_type=run_type)
        self._lock = threading.Lock()
        self._end_handlers: List[Callable[[AppMetrics], None]] = []

    def record(self, step: OpStep, duration_secs: float) -> None:
        with self._lock:
            cur = self.metrics.step_metrics.get(step.name)
            if cur is None:
                self.metrics.step_metrics[step.name] = StepMetrics(
                    step.name, duration_secs)
            else:
                cur.duration_secs += duration_secs
                cur.count += 1

    def add_application_end_handler(
            self, fn: Callable[[AppMetrics], None]) -> None:
        """OpWorkflowRunner.addApplicationEndHandler (:145) parity."""
        self._end_handlers.append(fn)

    def finish(self) -> AppMetrics:
        self.metrics.app_end_time = time.time()
        for fn in self._end_handlers:
            try:
                fn(self.metrics)
            except Exception:  # handlers must not break the run
                pass
        return self.metrics


_local = threading.local()


def current_collector() -> Optional[MetricsCollector]:
    return getattr(_local, "collector", None)


@contextlib.contextmanager
def install_collector(collector: MetricsCollector):
    """Make ``collector`` the thread-current one for the enclosed block
    WITHOUT recording a step for the block itself (the run's total lives in
    AppMetrics.app_duration; steps are for attributed time only)."""
    prev = current_collector()
    _local.collector = collector
    try:
        yield collector
    finally:
        _local.collector = prev


@contextlib.contextmanager
def with_job_group(step: OpStep, collector: Optional[MetricsCollector] = None):
    """Label a phase of the run (JobGroupUtil.withJobGroup parity).

    The first entered group installs its collector as the thread-current one
    so nested library code can record into the same run.
    """
    coll = collector or current_collector()
    installed = False
    if coll is not None and current_collector() is None:
        _local.collector = coll
        installed = True
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        if coll is not None:
            coll.record(step, dt)
        if installed:
            _local.collector = None


@dataclass
class RunCounters:
    """Transfer / dispatch accounting for one run.

    Uploads and fetches are counted at the framework's own transfer sites
    (``trees._dev_memo`` builds, ``validators._materialize``, binned-matrix
    uploads); ``upload_s``/``fetch_s`` time the enqueuing call — through a
    remote-device tunnel that call blocks for most of the wire time, so
    these are honest lower bounds on transfer cost.  ``drain_s`` separates
    QUEUE-DRAIN from transfer at the fetch sites (``fetch_timed``): a
    stacked metric fetch after an async sweep blocks first on the enqueued
    device work finishing, and booking that wait as "fetch" misdirected
    round-3's optimization targeting (VERDICT r3 Weak #6) — drain is
    compute-to-wait-for, fetch is bytes-on-the-wire.  On backends where
    ``block_until_ready`` returns early (the tunneled axon TPU — see
    ``fetch_timed``), ``drain_s`` under-attributes and ``fetch_s`` may
    still include drain: read the split as a lower bound on drain.  ``launches`` counts
    explicit kernel dispatches at our call sites (tree-growth chunks,
    grid-solver programs, scoring programs) — a design-level dispatch
    count, not an XLA op count.
    """

    upload_bytes: int = 0
    upload_s: float = 0.0
    uploads: int = 0
    fetch_bytes: int = 0
    fetch_s: float = 0.0
    fetches: int = 0
    drain_s: float = 0.0
    drains: int = 0
    launches: int = 0
    launch_tags: Dict[str, int] = field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        return {
            "uploadBytes": self.upload_bytes,
            "uploadSecs": round(self.upload_s, 3),
            "uploads": self.uploads,
            "fetchBytes": self.fetch_bytes,
            "fetchSecs": round(self.fetch_s, 3),
            "fetches": self.fetches,
            "drainSecs": round(self.drain_s, 3),
            "drains": self.drains,
            "launches": self.launches,
            "launchTags": dict(self.launch_tags),
        }


COUNTERS = RunCounters()


def reset_counters() -> RunCounters:
    """Zero the global transfer/dispatch counters; returns the new object."""
    global COUNTERS
    COUNTERS = RunCounters()
    return COUNTERS


def count_upload(nbytes: int, seconds: float) -> None:
    COUNTERS.upload_bytes += int(nbytes)
    COUNTERS.upload_s += seconds
    COUNTERS.uploads += 1


def count_fetch(nbytes: int, seconds: float) -> None:
    COUNTERS.fetch_bytes += int(nbytes)
    COUNTERS.fetch_s += seconds
    COUNTERS.fetches += 1


def count_drain(seconds: float) -> None:
    COUNTERS.drain_s += seconds
    COUNTERS.drains += 1


def count_launch(tag: str, n: int = 1) -> None:
    COUNTERS.launches += n
    COUNTERS.launch_tags[tag] = COUNTERS.launch_tags.get(tag, 0) + n


def fetch_timed(x, dtype=None):
    """Device→host fetch with drain/transfer split accounting.

    ``block_until_ready`` first (time booked as ``drain_s`` — the async
    queue finishing its enqueued compute), then the actual ``np.asarray``
    copy (booked as ``fetch_s`` against the fetched bytes).  Plain
    ``np.asarray`` conflated the two, which at r3's default grid booked
    ~42 s of sweep compute as "fetch time".

    Platform caveat (ADVICE r4): on the tunneled axon TPU backend,
    ``block_until_ready`` has been observed to return EARLY — the
    subsequent ``np.asarray`` then still blocks for queue drain.  There
    ``drain_s`` is a LOWER bound and ``fetch_s`` may still include drain;
    treat the split as directional, not definitive, when targeting
    optimizations."""
    import numpy as np

    t0 = time.perf_counter()
    try:
        x.block_until_ready()
    except AttributeError:  # host value already
        pass
    t1 = time.perf_counter()
    out = np.asarray(x) if dtype is None else np.asarray(x, dtype)
    t2 = time.perf_counter()
    count_drain(t1 - t0)
    count_fetch(out.nbytes, t2 - t1)
    return out


@dataclass
class StageProfile:
    """One executed DAG stage, as recorded by the execution plan
    (workflow/plan.py) — the per-stage analogue of the reference's
    OpSparkListener stage metrics, with TPU-relevant extras: device
    launches dispatched (from ``RunCounters``) and the dataset's column
    delta (liveness accounting)."""

    uid: str
    op: str
    output: str
    layer: int
    kind: str            # "fit" | "transform" | "substitute"
    device_heavy: bool
    wall_s: float
    rows: int
    cols_added: int = 0
    cols_dropped: int = 0   # columns freed after this stage's layer
    launches: int = 0       # device dispatches attributed (serial stages only)

    def to_json(self) -> Dict[str, Any]:
        return {"uid": self.uid, "op": self.op, "output": self.output,
                "layer": self.layer, "kind": self.kind,
                "deviceHeavy": self.device_heavy,
                "wallSecs": round(self.wall_s, 4), "rows": self.rows,
                "colsAdded": self.cols_added,
                "colsDropped": self.cols_dropped, "launches": self.launches}


class PlanProfiler:
    """Accumulates StageProfile entries for one plan execution; thread-safe
    (host-side stages record from pool threads).  Also tracks the peak
    resident column count — the number liveness pruning exists to bound."""

    def __init__(self):
        self.stages: List[StageProfile] = []
        self.peak_columns: int = 0
        self.final_columns: int = 0
        self.wall_s: float = 0.0
        self.layer_drops: Dict[int, List[str]] = {}
        self._lock = threading.Lock()

    def record_stage(self, sp: StageProfile) -> None:
        with self._lock:
            self.stages.append(sp)

    def note_columns(self, count: int) -> None:
        with self._lock:
            self.peak_columns = max(self.peak_columns, count)
            self.final_columns = count

    def note_drops(self, layer: int, names: List[str]) -> None:
        with self._lock:
            self.layer_drops.setdefault(layer, []).extend(names)

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            stages = sorted(self.stages, key=lambda s: (s.layer, s.output))
            return {
                "wallSecs": round(self.wall_s, 4),
                "peakColumns": self.peak_columns,
                "finalColumns": self.final_columns,
                "layerDrops": {str(k): list(v) for k, v in
                               sorted(self.layer_drops.items())},
                "stages": [s.to_json() for s in stages],
            }

    def format(self, top_k: int = 20) -> str:
        """Human-readable per-stage summary (workflow.train(profile=True))."""
        with self._lock:
            stages = list(self.stages)
            peak, final, wall = (self.peak_columns, self.final_columns,
                                 self.wall_s)
        lines = [f"plan execution: {len(stages)} stages, "
                 f"{wall:.3f}s wall, peak {peak} resident columns "
                 f"(final {final})"]
        by_cost = sorted(stages, key=lambda s: -s.wall_s)[:top_k]
        for s in by_cost:
            lines.append(
                f"  [{s.layer}] {s.kind:<9} {s.op:<24} {s.wall_s*1e3:8.1f} ms"
                f"  rows={s.rows}  +{s.cols_added}/-{s.cols_dropped} cols"
                + (f"  launches={s.launches}" if s.launches else "")
                + ("  [device]" if s.device_heavy else ""))
        return "\n".join(lines)


@contextlib.contextmanager
def profile_to(log_dir: str):
    """Capture an XLA device trace for the enclosed block (the TPU analogue
    of the Spark UI): view with TensorBoard's profile plugin or Perfetto."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
