"""Sensitive-feature metadata records.

Reference: ``SensitiveFeatureInformation`` / ``SensitiveNameInformation`` /
``GenderDetectionResults`` (utils/src/main/scala/com/salesforce/op/
SensitiveFeatureInformation.scala:47-161): per raw feature (and optional map
key), a record of detected sensitive content — e.g. human names with
name-probability and per-strategy gender-detection results — plus whether the
framework acted on the detection (dropped/ignored the feature). Stored in
vector metadata and surfaced through ModelInsights.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

__all__ = ["SensitiveFeatureInformation", "SensitiveNameInformation",
           "GenderDetectionResults", "sensitive_map_to_json",
           "sensitive_map_from_json"]


@dataclasses.dataclass
class GenderDetectionResults:
    """One gender-detection strategy's outcome (reference :150-161)."""

    strategy: str
    pct_unidentified: float

    def to_json(self) -> dict:
        return {"strategyString": self.strategy,
                "pctUnidentified": self.pct_unidentified}

    @staticmethod
    def from_json(d: dict) -> "GenderDetectionResults":
        return GenderDetectionResults(d["strategyString"],
                                      float(d["pctUnidentified"]))


@dataclasses.dataclass
class SensitiveFeatureInformation:
    """Base record: which feature (and map key) is sensitive and whether the
    detection changed the pipeline (reference :47-59)."""

    name: str
    key: Optional[str] = None
    action_taken: bool = False

    ENTRY_NAME = "SensitiveFeatureInformation"

    def to_json(self) -> dict:
        return {"DetectedSensitiveFeatureKind": self.ENTRY_NAME,
                "FeatureName": self.name, "MapKey": self.key,
                "ActionTaken": self.action_taken}

    @staticmethod
    def from_json(d: dict) -> "SensitiveFeatureInformation":
        kind = d.get("DetectedSensitiveFeatureKind",
                     SensitiveFeatureInformation.ENTRY_NAME)
        if kind == SensitiveNameInformation.ENTRY_NAME:
            return SensitiveNameInformation(
                name=d["FeatureName"], key=d.get("MapKey"),
                action_taken=bool(d.get("ActionTaken", False)),
                prob_name=float(d.get("ProbName", 0.0)),
                gender_detect_strats=[
                    GenderDetectionResults.from_json(g)
                    for g in d.get("GenderDetectStrats", [])],
                prob_male=float(d.get("ProbMale", 0.0)),
                prob_female=float(d.get("ProbFemale", 0.0)),
                prob_other=float(d.get("ProbOther", 0.0)))
        return SensitiveFeatureInformation(
            name=d["FeatureName"], key=d.get("MapKey"),
            action_taken=bool(d.get("ActionTaken", False)))


@dataclasses.dataclass
class SensitiveNameInformation(SensitiveFeatureInformation):
    """Human-name detection record (reference :114-148)."""

    prob_name: float = 0.0
    gender_detect_strats: List[GenderDetectionResults] = \
        dataclasses.field(default_factory=list)
    prob_male: float = 0.0
    prob_female: float = 0.0
    prob_other: float = 0.0

    ENTRY_NAME = "SensitiveNameInformation"

    def to_json(self) -> dict:
        d = super().to_json()
        d.update({"ProbName": self.prob_name,
                  "GenderDetectStrats": [g.to_json()
                                         for g in self.gender_detect_strats],
                  "ProbMale": self.prob_male, "ProbFemale": self.prob_female,
                  "ProbOther": self.prob_other})
        return d


def sensitive_map_to_json(
        m: Dict[str, List[SensitiveFeatureInformation]]) -> dict:
    """Map of feature name -> records, as one JSON-able dict (reference
    ``SensitiveFeatureInformation.toMetadata`` :67-77)."""
    return {k: [s.to_json() for s in v] for k, v in m.items()}


def sensitive_map_from_json(
        d: dict) -> Dict[str, List[SensitiveFeatureInformation]]:
    return {k: [SensitiveFeatureInformation.from_json(s) for s in v]
            for k, v in d.items()}
