"""Mergeable streaming sketches — the accumulators behind chunked fitting.

Reference: the monoid aggregator design the reference uses for its
streaming/aggregate readers (``MonoidAggregatorDefaults``) and the
external-memory two-pass fit of "XGBoost: Scalable GPU Accelerated
Learning" (arXiv:1806.11248): statistics that must survive an out-of-core
pass are kept as small mergeable states, updated one bounded chunk at a
time, and combined associatively.

Three sketches cover the hot fitters (see stages/base.py streaming-fit
protocol):

* ``WelfordMoments`` — per-column (count, mean, M2, min, max) via Chan's
  parallel update: numerically stable streaming moments whose mean/variance
  match a one-shot float64 computation to ~1e-12 relative (documented
  tolerance; chunked summation order differs from numpy's pairwise sum in
  the last ulps).
* ``PearsonSketch`` — adds the label co-moment C = Σ(x-mx)(y-my) with the
  same merge algebra, yielding streaming Pearson correlations.
* ``TopKSketch`` — mergeable value counting with first-seen ordering.  With
  ``capacity=None`` (the default used by the vectorizers) counting is EXACT
  and ``top_k()`` reproduces ``collections.Counter.most_common`` including
  its tie order (ties break by first occurrence).  A bounded ``capacity``
  switches to space-saving eviction (count-min style overestimates, error
  bounded by the smallest retained count) for adversarially wide columns.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["WelfordMoments", "PearsonSketch", "TopKSketch"]


def _chan_merge(n_a: float, mean_a, m2_a, n_b: float, mean_b, m2_b):
    """Merge two (count, mean, M2) moment states (Chan et al. 1979)."""
    n = n_a + n_b
    if n == 0:
        return 0.0, mean_a, m2_a
    delta = mean_b - mean_a
    mean = mean_a + delta * (n_b / n)
    m2 = m2_a + m2_b + delta * delta * (n_a * n_b / n)
    return n, mean, m2


class WelfordMoments:
    """Streaming per-column moments over row chunks.

    Shape-agnostic: the first ``update`` fixes the column shape — a 1-D
    chunk gives scalar stats, an (n, d) chunk gives d-vector stats.  All
    accumulation is float64.
    """

    def __init__(self):
        self.n: float = 0.0
        self.mean = None
        self.m2 = None
        self.min = None
        self.max = None

    def update(self, values) -> "WelfordMoments":
        x = np.asarray(values, dtype=np.float64)
        if x.shape[0] == 0:
            return self
        n_b = float(x.shape[0])
        mean_b = x.mean(axis=0)
        m2_b = ((x - mean_b) ** 2).sum(axis=0)
        return self._merge_stats(n_b, mean_b, m2_b, x.min(axis=0),
                                 x.max(axis=0))

    def _merge_stats(self, n_b, mean_b, m2_b, min_b, max_b
                     ) -> "WelfordMoments":
        """Fold precomputed chunk stats in (the sketches that already hold
        centered chunk data use this to avoid a second pass)."""
        if self.mean is None:
            self.n, self.mean, self.m2 = n_b, mean_b, m2_b
            self.min, self.max = min_b, max_b
        else:
            self.n, self.mean, self.m2 = _chan_merge(
                self.n, self.mean, self.m2, n_b, mean_b, m2_b)
            self.min = np.minimum(self.min, min_b)
            self.max = np.maximum(self.max, max_b)
        return self

    def merge(self, other: "WelfordMoments") -> "WelfordMoments":
        if other.mean is None:
            return self
        if self.mean is None:
            self.n, self.mean, self.m2 = other.n, other.mean, other.m2
            self.min, self.max = other.min, other.max
            return self
        self.n, self.mean, self.m2 = _chan_merge(
            self.n, self.mean, self.m2, other.n, other.mean, other.m2)
        self.min = np.minimum(self.min, other.min)
        self.max = np.maximum(self.max, other.max)
        return self

    def variance(self, ddof: int = 1):
        denom = self.n - ddof
        if self.mean is None or denom <= 0:
            return (np.zeros_like(self.mean)
                    if self.mean is not None else 0.0)
        return self.m2 / denom

    # -- checkpoint codec hooks (workflow/checkpoint.py) --------------------

    def to_state(self) -> dict:
        """Loss-free snapshot for checkpointing: every field is a float,
        ndarray or None, so the persistence array-externalization encoding
        round-trips it bit-exactly (resume parity depends on this)."""
        return {"n": self.n, "mean": self.mean, "m2": self.m2,
                "min": self.min, "max": self.max}

    @classmethod
    def from_state(cls, state: dict) -> "WelfordMoments":
        out = cls()
        out.n = state["n"]
        out.mean = state["mean"]
        out.m2 = state["m2"]
        out.min = state["min"]
        out.max = state["max"]
        return out


class PearsonSketch:
    """Streaming column-vs-label Pearson: x-moments, y-moments, co-moment."""

    def __init__(self):
        self.x = WelfordMoments()
        self.y = WelfordMoments()
        self.c = None  # Σ (x - mean_x)(y - mean_y), shape (d,)

    def update(self, X, y) -> "PearsonSketch":
        # one float64 working copy, centered IN PLACE, then BLAS products —
        # the chunk cost is ~3 passes over the block instead of the naive
        # ~8 temporaries (this runs per chunk on the train hot path)
        if np.asarray(X).shape[0] == 0:
            return self
        min_b = np.asarray(X).min(axis=0).astype(np.float64)
        max_b = np.asarray(X).max(axis=0).astype(np.float64)
        Xd = np.array(X, dtype=np.float64)   # owned copy (centered below)
        yd = np.asarray(y, dtype=np.float64)
        n_b = float(Xd.shape[0])
        mean_xb = Xd.mean(axis=0)
        mean_yb = yd.mean()
        Xd -= mean_xb
        yc = yd - mean_yb
        m2_b = np.einsum("ij,ij->j", Xd, Xd)
        c_b = yc @ Xd
        m2y_b = float(yc @ yc)
        if self.c is None:
            self.c = c_b
        else:
            n_a = self.x.n
            delta_x = mean_xb - self.x.mean
            delta_y = mean_yb - self.y.mean
            self.c = (self.c + c_b
                      + delta_x * delta_y * (n_a * n_b / (n_a + n_b)))
        self.x._merge_stats(n_b, mean_xb, m2_b, min_b, max_b)
        self.y._merge_stats(n_b, mean_yb, m2y_b, float(yd.min()),
                            float(yd.max()))
        return self

    def merge(self, other: "PearsonSketch") -> "PearsonSketch":
        if other.c is None:
            return self
        if self.c is None:
            self.c = other.c
            self.x.merge(other.x)
            self.y.merge(other.y)
            return self
        n_a, n_b = self.x.n, other.x.n
        delta_x = other.x.mean - self.x.mean
        delta_y = other.y.mean - self.y.mean
        self.c = (self.c + other.c
                  + delta_x * delta_y * (n_a * n_b / (n_a + n_b)))
        self.x.merge(other.x)
        self.y.merge(other.y)
        return self

    def to_state(self) -> dict:
        """Checkpoint snapshot (see WelfordMoments.to_state)."""
        return {"x": self.x.to_state(), "y": self.y.to_state(),
                "c": self.c}

    @classmethod
    def from_state(cls, state: dict) -> "PearsonSketch":
        out = cls()
        out.x = WelfordMoments.from_state(state["x"])
        out.y = WelfordMoments.from_state(state["y"])
        out.c = state["c"]
        return out

    def correlation(self) -> np.ndarray:
        """Pearson r per column, mirroring the SanityChecker host path's
        guards: eps-clamped denominators, NaN -> 0."""
        if self.c is None:
            return np.zeros(0, np.float64)
        n = self.x.n
        var_x = self.x.variance(ddof=1)
        den = (np.sqrt(np.maximum(var_x, 1e-30) * max(n - 1, 1))
               * np.sqrt(max(float(self.y.m2), 1e-30)))
        with np.errstate(invalid="ignore", divide="ignore"):
            return np.nan_to_num(self.c / den)


class TopKSketch:
    """Mergeable top-k value counting with Counter-compatible ordering.

    State per key: (count, first_seen) where ``first_seen`` is a global
    monotone position (chunk offset + within-chunk first index), so
    ``top_k()``'s tie-break — smaller first_seen wins — reproduces
    ``Counter.most_common`` (insertion order) exactly when counting is
    exact.  ``add_chunk`` consumes one chunk's values vectorized via
    ``np.unique``; ``offset`` advances by the number of items added.

    ``capacity=None`` (default): exact counting — what the vectorizers use.
    Bounded ``capacity``: space-saving eviction — the smallest-count entry
    is replaced and the newcomer inherits its count as an overestimate
    (``error`` records the worst-case overcount, count-min style).
    """

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = capacity
        self.counts: Dict[object, List[float]] = {}  # key -> [count, first]
        self.offset: int = 0
        self.error: float = 0.0

    def add_chunk(self, values: Sequence) -> "TopKSketch":
        arr = np.asarray(values, dtype=object)
        if arr.size:
            uniq, first_idx, cnt = np.unique(
                arr, return_index=True, return_counts=True)
            self._absorb(uniq, cnt.astype(np.float64),
                         first_idx + self.offset)
        self.offset += int(arr.size)
        return self

    def _absorb(self, keys, counts, first_seen) -> None:
        for k, c, fs in zip(keys, counts, first_seen):
            ent = self.counts.get(k)
            if ent is not None:
                ent[0] += c
                if fs < ent[1]:
                    ent[1] = fs
            elif self.capacity is None or len(self.counts) < self.capacity:
                self.counts[k] = [float(c), float(fs)]
            else:  # space-saving eviction
                victim = min(self.counts, key=lambda v: self.counts[v][0])
                base = self.counts.pop(victim)[0]
                self.error = max(self.error, base)
                self.counts[k] = [base + float(c), float(fs)]

    def merge(self, other: "TopKSketch") -> "TopKSketch":
        # the right operand's first_seen positions shift past this sketch's
        # item span, preserving global first-occurrence order
        keys = list(other.counts)
        counts = [other.counts[k][0] for k in keys]
        firsts = [other.counts[k][1] + self.offset for k in keys]
        self._absorb(np.asarray(keys, object), counts, firsts)
        self.offset += other.offset
        self.error = max(self.error, other.error)
        return self

    def to_state(self) -> dict:
        """Checkpoint snapshot.  Keys and [count, first_seen] pairs are
        kept in dict insertion order: the bounded-capacity eviction picks
        ``min`` over iteration order on ties, so order preservation keeps
        resumed runs byte-identical to uninterrupted ones."""
        return {"capacity": self.capacity, "offset": self.offset,
                "error": self.error,
                "keys": list(self.counts.keys()),
                "entries": [list(v) for v in self.counts.values()]}

    @classmethod
    def from_state(cls, state: dict) -> "TopKSketch":
        out = cls(capacity=state["capacity"])
        out.offset = int(state["offset"])
        out.error = float(state["error"])
        out.counts = {k: [float(c), float(f)]
                      for k, (c, f) in zip(state["keys"], state["entries"])}
        return out

    def top_k(self, k: int, min_support: float = 0.0) -> List:
        """The ``Counter.most_common(k)`` analogue: top k keys by count
        (ties by first occurrence), then min-support filtered — matching
        the vectorizers' ``most_common`` + filter idiom."""
        ordered = sorted(self.counts.items(),
                         key=lambda kv: (-kv[1][0], kv[1][1]))
        return [key for key, (c, _) in ordered[:k] if c >= min_support]
