"""Bounded streaming histogram (Ben-Haim / Tom-Tov style).

Reference: the in-tree Java ``StreamingHistogram`` used by
``FeatureDistribution`` for numeric raw-feature profiling
(utils/src/main/java/com/salesforce/op/utils/stats/StreamingHistogram.java:36,
120-280; consumed at filters/FeatureDistribution.scala:235).

Vectorized redesign (SURVEY §2.11 port plan): instead of the Java point-at-a-
time insert + closest-pair merge, batches are absorbed whole — append the
batch's (sorted) values as unit bins, then repeatedly merge the smallest-gap
*disjoint* adjacent pairs in vectorized passes until the bin budget holds.
Each pass merges up to half the excess, so the loop is O(log excess) numpy
passes rather than O(points) scalar merges.  The invariants the estimator
relies on are preserved: centroids are count-weighted means, counts are
conserved, and bins stay sorted.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = ["StreamingHistogram"]


class StreamingHistogram:
    def __init__(self, max_bins: int = 100):
        if max_bins < 2:
            raise ValueError("max_bins must be >= 2")
        self.max_bins = max_bins
        self.centroids = np.zeros(0, np.float64)
        self.counts = np.zeros(0, np.float64)

    # -- updates ------------------------------------------------------------

    def update(self, values) -> "StreamingHistogram":
        """Absorb a batch of finite values (NaN/inf ignored).

        Delegates the insert+shrink loop to the native C++ backend when
        available (~4x on 1M-value batches, ~9x on point streams); the
        vectorized numpy path below is the behavioral reference/fallback.
        """
        v = np.asarray(values, np.float64).ravel()
        v = v[np.isfinite(v)]
        if v.size == 0:
            return self
        from .. import native
        if native.AVAILABLE:
            h = native.NativeStreamingHistogram(self.max_bins)
            if self.centroids.size:
                h.load(self.centroids, self.counts)
            h.update(v)
            self.centroids, self.counts = h.bins
            return self
        # pre-aggregate duplicates (cheap and common for integral columns)
        uniq, cnt = np.unique(v, return_counts=True)
        self.centroids = np.concatenate([self.centroids, uniq])
        self.counts = np.concatenate([self.counts, cnt.astype(np.float64)])
        order = np.argsort(self.centroids, kind="stable")
        self.centroids = self.centroids[order]
        self.counts = self.counts[order]
        self._shrink()
        return self

    def merge(self, other: "StreamingHistogram") -> "StreamingHistogram":
        """Monoid combine (the distribution-reduce path)."""
        out = StreamingHistogram(max(self.max_bins, other.max_bins))
        cs = np.concatenate([self.centroids, other.centroids])
        ns = np.concatenate([self.counts, other.counts])
        order = np.argsort(cs, kind="stable")
        out.centroids, out.counts = cs[order], ns[order]
        out._shrink()
        return out

    def _shrink(self) -> None:
        while self.centroids.size > self.max_bins:
            c, n = self.centroids, self.counts
            excess = c.size - self.max_bins
            gaps = np.diff(c)                          # (len-1,)
            # rank pairs by gap; greedily take disjoint pairs (a pair uses
            # bins i and i+1) smallest-first, up to the excess
            order = np.argsort(gaps, kind="stable")
            take = np.zeros(gaps.size, bool)
            used = np.zeros(c.size, bool)
            budget = max(1, min(excess, c.size // 2))
            for i in order:
                if budget == 0:
                    break
                if not used[i] and not used[i + 1]:
                    take[i] = True
                    used[i] = used[i + 1] = True
                    budget -= 1
            left = np.where(take)[0]
            tot = n[left] + n[left + 1]
            merged_c = (c[left] * n[left] + c[left + 1] * n[left + 1]) / tot
            keep = ~used[:c.size]
            new_c = np.concatenate([c[keep], merged_c])
            new_n = np.concatenate([n[keep], tot])
            order2 = np.argsort(new_c, kind="stable")
            self.centroids, self.counts = new_c[order2], new_n[order2]

    # -- queries ------------------------------------------------------------

    @property
    def total(self) -> float:
        return float(self.counts.sum())

    def density(self, grid: np.ndarray) -> np.ndarray:
        """Probability mass assigned to each cell of a sorted grid
        (each centroid's count falls into the cell containing it)."""
        if self.total == 0:
            return np.zeros(len(grid), np.float64)
        idx = np.clip(np.searchsorted(grid, self.centroids, side="right") - 1,
                      0, len(grid) - 1)
        out = np.zeros(len(grid), np.float64)
        np.add.at(out, idx, self.counts)
        return out / out.sum()

    def quantile(self, q: float) -> float:
        if self.total == 0:
            return float("nan")
        cum = np.cumsum(self.counts)
        i = int(np.searchsorted(cum, q * cum[-1]))
        return float(self.centroids[min(i, self.centroids.size - 1)])

    @property
    def bounds(self) -> Tuple[float, float]:
        if self.centroids.size == 0:
            return (float("nan"), float("nan"))
        return float(self.centroids[0]), float(self.centroids[-1])

    def to_json(self) -> dict:
        return {"maxBins": self.max_bins,
                "centroids": self.centroids.tolist(),
                "counts": self.counts.tolist()}

    @staticmethod
    def from_json(d: dict) -> "StreamingHistogram":
        h = StreamingHistogram(d["maxBins"])
        h.centroids = np.asarray(d["centroids"], np.float64)
        h.counts = np.asarray(d["counts"], np.float64)
        return h

    # -- checkpoint codec hooks (workflow/checkpoint.py) --------------------

    def to_state(self) -> dict:
        """Loss-free snapshot: centroids/counts persist as float64 arrays
        (npz externalization), so a resumed fit's bins are bit-identical."""
        return {"max_bins": self.max_bins,
                "centroids": self.centroids, "counts": self.counts}

    @classmethod
    def from_state(cls, state: dict) -> "StreamingHistogram":
        h = cls(int(state["max_bins"]))
        h.centroids = np.asarray(state["centroids"], np.float64)
        h.counts = np.asarray(state["counts"], np.float64)
        return h

    @classmethod
    def from_value_counts(cls, values, counts,
                          max_bins: int = 32) -> "StreamingHistogram":
        """Build from exact (value, count) pairs (the mode-count fitters'
        states) — bins are the values themselves, shrunk to the budget."""
        h = cls(max_bins)
        v = np.asarray(values, np.float64)
        c = np.asarray(counts, np.float64)
        finite = np.isfinite(v)
        v, c = v[finite], c[finite]
        order = np.argsort(v, kind="stable")
        h.centroids, h.counts = v[order], c[order]
        h._shrink()
        return h
