"""Stage/feature UID generation.

Reference: utils/src/main/scala/com/salesforce/op/UID.scala — UIDs of the form
``ClassName_000000000001`` from a process-wide counter, with reset support for
deterministic tests.
"""
from __future__ import annotations

import itertools
import re
import threading
from typing import Dict, Tuple

_counter = itertools.count(1)
_lock = threading.Lock()

_UID_RE = re.compile(r"^(\w+)_(\w+)$")


def uid_for(cls_or_name) -> str:
    name = cls_or_name if isinstance(cls_or_name, str) else cls_or_name.__name__
    with _lock:
        n = next(_counter)
    return f"{name}_{n:012x}"


def reset_uids(start: int = 1) -> None:
    """Reset the counter (tests only)."""
    global _counter
    with _lock:
        _counter = itertools.count(start)


def parse_uid(uid: str) -> Tuple[str, str]:
    m = _UID_RE.match(uid)
    if not m:
        raise ValueError(f"invalid uid {uid!r}")
    return m.group(1), m.group(2)


def count_uids(uids) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for u in uids:
        name, _ = parse_uid(u)
        out[name] = out.get(name, 0) + 1
    return out
