"""Build/version stamping for model artifacts.

Reference: ``VersionInfo`` (utils/src/main/scala/com/salesforce/op/utils/
version/VersionInfo.scala:50-89): a properties-backed record (version, build
time, git branch/commit, toolchain versions) attached to saved models and
logs. Here the toolchain is Python/JAX and the git commit is read lazily
from the repo if present.
"""
from __future__ import annotations

import dataclasses
import functools
import platform
import subprocess
from typing import Optional

__all__ = ["VersionInfo", "version_info", "VERSION"]

VERSION = "0.1.0"


@dataclasses.dataclass(frozen=True)
class VersionInfo:
    version: str
    python_version: str
    jax_version: Optional[str] = None
    git_branch: Optional[str] = None
    git_commit: Optional[str] = None

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(d: dict) -> "VersionInfo":
        fields = {f.name for f in dataclasses.fields(VersionInfo)}
        return VersionInfo(**{k: v for k, v in d.items() if k in fields})


def _git(*args: str) -> Optional[str]:
    import os
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"], capture_output=True,
            text=True, timeout=5, cwd=pkg_dir)
        # only stamp git info for a development checkout of THIS framework —
        # a pip install inside someone else's repo (./venv under a project
        # root) would otherwise resolve the user's repo HEAD
        if (top.returncode != 0 or not top.stdout.strip() or not
                os.path.isdir(os.path.join(top.stdout.strip(),
                                           "transmogrifai_tpu"))):
            return None
        out = subprocess.run(["git", *args], capture_output=True, text=True,
                             timeout=5, cwd=pkg_dir)
        return out.stdout.strip() or None if out.returncode == 0 else None
    except (OSError, subprocess.TimeoutExpired):  # pragma: no cover
        return None


@functools.lru_cache(maxsize=1)
def version_info() -> VersionInfo:
    try:
        import jax
        jax_version = jax.__version__
    except Exception:  # pragma: no cover
        jax_version = None
    return VersionInfo(
        version=VERSION,
        python_version=platform.python_version(),
        jax_version=jax_version,
        git_branch=_git("rev-parse", "--abbrev-ref", "HEAD"),
        git_commit=_git("rev-parse", "--short", "HEAD"),
    )
