from .workflow import OpWorkflow, OpWorkflowModel  # noqa: F401
from .dag import (compute_dag, cut_dag_cv, fit_and_transform_dag,  # noqa: F401
                  transform_dag)
from .runner import (OpApp, OpParams, OpWorkflowRunner,  # noqa: F401
                     OpWorkflowRunnerResult, RunType)
