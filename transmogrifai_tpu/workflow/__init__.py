from .workflow import OpWorkflow, OpWorkflowModel  # noqa: F401
from .dag import (compute_dag, cut_dag_cv, fit_and_transform_dag,  # noqa: F401
                  transform_dag)
from .plan import ExecutionPlan, plan_for  # noqa: F401
from .runner import (OpApp, OpParams, OpWorkflowRunner,  # noqa: F401
                     OpWorkflowRunnerResult, RunType)
