from .workflow import OpWorkflow, OpWorkflowModel  # noqa: F401
from .dag import compute_dag, fit_and_transform_dag, transform_dag  # noqa: F401
