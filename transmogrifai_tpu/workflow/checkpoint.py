"""Chunk-level checkpoint/resume for the streaming two-pass fit.

Reference: Spark gave the original TransmogrifAI lineage-based recomputation
— a lost executor replayed its partitions from source.  The TPU port's
out-of-core driver (workflow/streaming.py) has no lineage, so before this
module a process kill at hour N of a long fit lost all N hours.  The fix
exploits what the streaming-fit protocol already guarantees: per-estimator
states are MERGEABLE MONOIDS (stages/base.py begin_fit/update_chunk/
merge_states), so the complete progress of a reader fit pass is just
{per-estimator state, chunks-consumed cursor} — small, serializable, and
exact.

Layout of ``checkpoint_dir``::

  checkpoint.json   the manifest: format version, run fingerprint,
                    completed passes (fitted models as persistence stage
                    records), and the in-flight pass cursor + states
  state-<seq>.npz   every ndarray, externalized exactly like
                    workflow/persistence.py's arrays.npz

Atomicity: each save writes a NEW ``state-<seq>.npz``, then the manifest to
a temp file, then ``os.replace``s it over ``checkpoint.json`` — a crash at
any byte leaves the previous checkpoint fully intact (the old npz is only
deleted after the rename lands).

What resumes where (documented in docs/robustness.md):

* **mid-pass** — pure fit passes (the pre-fuse estimator layers, typically
  the expensive first featurization pass) checkpoint every
  ``every_chunks`` chunks; resume restores states bit-exactly and
  fast-skips the consumed chunks (they are re-read but not re-transformed
  or re-fitted).
* **pass boundary** — every completed pre-fuse pass persists its fitted
  models (persistence stage records); resume adopts them and never
  re-runs the pass.
* **fused pass onward** — the fused fit+materialize pass writes full-length
  output buffers that are deliberately NOT checkpointed (they are the
  size of the dataset); a crash there resumes from the last pass
  boundary and re-runs the fused pass.

Fingerprinting: the manifest records the reader identity (path/size/mtime
or in-memory shape), ``chunk_rows``, and the DAG stage list.  A resume
against a different dataset or pipeline raises
:class:`CheckpointMismatchError` instead of silently blending two runs.

The ``checkpoint.barrier`` fault-injection point (utils/faults.py) fires
after every durable save — the crash-resume tests SIGKILL there.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

from ..stages.base import Estimator, Model, PipelineStage
from ..utils import faults
from .persistence import _ArrayStore, _load_stage, _stage_record

__all__ = ["StreamingCheckpointManager", "CheckpointMismatchError",
           "ResumeState", "compute_fingerprint", "logical_fingerprint",
           "encode_fit_state", "decode_fit_state", "adopt_restored_model",
           "CHECKPOINT_JSON", "CHECKPOINT_VERSION",
           "SweepCheckpointManager", "sweep_fingerprint", "mesh_record",
           "fingerprint_diff", "SWEEP_CHECKPOINT_JSON",
           "BlockStripeStore"]

CHECKPOINT_JSON = "checkpoint.json"
CHECKPOINT_VERSION = 1
SWEEP_CHECKPOINT_JSON = "sweep.json"
#: v2: the fingerprint split into a LOGICAL sweep identity (compared on
#: resume) and an ADVISORY mesh record (recorded, never compared) — a
#: sweep preempted on 8 chips may resume on 4, or on one
SWEEP_CHECKPOINT_VERSION = 2


class CheckpointMismatchError(RuntimeError):
    """checkpoint_dir holds a checkpoint for a DIFFERENT run (other data,
    other pipeline, other chunk geometry).  Refusing to resume beats
    silently merging two trainings; point checkpoint_dir elsewhere or
    clear it."""


def fingerprint_diff(saved: Any, current: Any, path: str = "",
                     limit: int = 12) -> List[str]:
    """Key-level diff of two fingerprint documents — ``"path: saved=X
    current=Y"`` lines, so a mismatch message says WHICH keys diverged
    (a mesh-vs-logical mismatch is distinguishable at a glance) instead
    of dumping both fingerprints whole."""
    out: List[str] = []

    def walk(a: Any, b: Any, where: str) -> None:
        if len(out) >= limit:
            return
        if isinstance(a, dict) and isinstance(b, dict):
            for k in sorted(set(a) | set(b)):
                walk(a.get(k, "<absent>"), b.get(k, "<absent>"),
                     f"{where}.{k}" if where else str(k))
            return
        if isinstance(a, list) and isinstance(b, list):
            if len(a) != len(b):
                out.append(f"{where}: saved has {len(a)} item(s), "
                           f"current has {len(b)}")
                return
            for i, (x, y) in enumerate(zip(a, b)):
                walk(x, y, f"{where}[{i}]")
            return
        if a != b:
            out.append(f"{where}: saved={json.dumps(a, default=str)} "
                       f"current={json.dumps(b, default=str)}")

    walk(saved, current, path)
    if len(out) >= limit:
        out.append("... (diff truncated)")
    return out


def _mismatch_message(what: str, directory: str, saved: Any,
                      current: Any, hint: str) -> str:
    lines = fingerprint_diff(saved, current) or ["<no key-level diff>"]
    return (f"{what} in {directory!r} belongs to a different run; {hint}.\n"
            f"  differing keys:\n    " + "\n    ".join(lines))


# ---------------------------------------------------------------------------
# state codec — persistence-style array externalization + the small closed
# set of sketch/accumulator types the streaming fitters use
# ---------------------------------------------------------------------------

def _stateful_types() -> Dict[str, type]:
    """Classes with ``to_state``/``from_state`` checkpoint hooks, by name
    (lazy: vectorizers import jax-adjacent modules)."""
    from ..ops.vectorizers import TextStats
    from ..utils.sketches import PearsonSketch, TopKSketch, WelfordMoments
    from ..utils.streaming_histogram import StreamingHistogram

    return {"WelfordMoments": WelfordMoments, "PearsonSketch": PearsonSketch,
            "TopKSketch": TopKSketch, "TextStats": TextStats,
            "StreamingHistogram": StreamingHistogram}


def encode_fit_state(value: Any, key: str, store: _ArrayStore) -> Any:
    """Recursive JSON-able encoding of a streaming-fit state.

    ndarrays externalize into ``store`` (bit-exact npz round trip — resume
    parity requires it); registered sketches go through their
    ``to_state`` hooks; dicts with non-string keys (e.g. the mode-count
    ``{float: int}`` maps) become tagged ordered item lists.
    """
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, np.bool_):
        return bool(value)
    if isinstance(value, np.integer):
        return int(value)
    if isinstance(value, np.floating):
        return float(value)
    if isinstance(value, np.ndarray):
        return store.put(key, value)
    types = _stateful_types()
    name = type(value).__name__
    if name in types and isinstance(value, types[name]):
        return {"__state__": name,
                "payload": encode_fit_state(value.to_state(),
                                            f"{key}.{name}", store)}
    if isinstance(value, np.random.Generator):
        # the SanityChecker's row-sample stream must CONTINUE, not restart:
        # persist the bit generator's exact position
        return {"__rng__": {"bg": type(value.bit_generator).__name__,
                            "state": value.bit_generator.state}}
    from ..ops.vector_metadata import VectorMetadata

    if isinstance(value, VectorMetadata):
        return {"__vmeta__": value.to_json()}
    if isinstance(value, dict):
        if all(isinstance(k, str) for k in value):
            return {k: encode_fit_state(v, f"{key}.{k}", store)
                    for k, v in value.items()}
        return {"__items__": [
            [encode_fit_state(k, f"{key}.k{i}", store),
             encode_fit_state(v, f"{key}.v{i}", store)]
            for i, (k, v) in enumerate(value.items())]}
    if isinstance(value, (list, tuple)):
        return [encode_fit_state(v, f"{key}[{i}]", store)
                for i, v in enumerate(value)]
    raise TypeError(
        f"streaming-fit state at {key!r} holds a {type(value).__name__}, "
        f"which the checkpoint codec cannot persist; give the estimator "
        f"export_fit_state/import_fit_state hooks (stages/base.py) or the "
        f"type to_state/from_state")


def decode_fit_state(value: Any, arrays) -> Any:
    if isinstance(value, dict):
        if "__state__" in value:
            cls = _stateful_types()[value["__state__"]]
            return cls.from_state(decode_fit_state(value["payload"], arrays))
        if "__rng__" in value:
            spec = value["__rng__"]
            bg = getattr(np.random, spec["bg"])()
            bg.state = spec["state"]
            return np.random.Generator(bg)
        if "__vmeta__" in value:
            from ..ops.vector_metadata import VectorMetadata

            return VectorMetadata.from_json(value["__vmeta__"])
        if "__array__" in value:
            return arrays[value["__array__"]]
        if "__items__" in value:
            return {decode_fit_state(k, arrays): decode_fit_state(v, arrays)
                    for k, v in value["__items__"]}
        return {k: decode_fit_state(v, arrays) for k, v in value.items()}
    if isinstance(value, list):
        return [decode_fit_state(v, arrays) for v in value]
    return value


# ---------------------------------------------------------------------------
# run fingerprint
# ---------------------------------------------------------------------------

def _describe_reader(reader) -> Dict[str, Any]:
    # a host-sharded pod wrapper's LOGICAL identity is its source reader:
    # checkpoints written under one process count must resume under any
    # other (the pod record itself is advisory)
    inner = getattr(reader, "inner_reader", None)
    if inner is not None:
        reader = inner
    out: Dict[str, Any] = {"class": type(reader).__name__}
    # event-time readers (readers/aggregates.py, readers/events.py): the
    # logical identity is (cutoff spec, windows, source) — two readers over
    # the same file with different cutoffs produce different datasets, so
    # a resume across a cutoff change must invalidate
    if hasattr(reader, "key_fn") and hasattr(reader, "cutoff"):
        cutoff = reader.cutoff
        out["event"] = {
            "cutoffKind": getattr(cutoff, "kind", None),
            "cutoffMs": getattr(cutoff, "time_ms", None),
            "predictorWindowMs": reader.predictor_window_ms,
            "responseWindowMs": reader.response_window_ms,
            "conditional": getattr(reader, "target_condition",
                                   None) is not None,
        }
        source = getattr(reader, "source", None)
        if source is not None:
            from ..readers.base import Reader as _Reader

            if isinstance(source, _Reader):
                out["source"] = _describe_reader(source)
            elif hasattr(source, "to_dict") and hasattr(source, "columns"):
                out["source"] = {"rows": int(len(source)),
                                 "columns": [str(c) for c in source.columns]}
            elif isinstance(source, (list, tuple)):
                out["source"] = {"rows": len(source)}
        return out
    for attr in ("path", "csv_path"):
        path = getattr(reader, attr, None)
        if isinstance(path, str):
            out["path"] = path
            try:
                st = os.stat(path)
                out["size"] = st.st_size
                out["mtime"] = int(st.st_mtime)
            except OSError:
                pass
            return out
    df = getattr(reader, "df", None)
    if df is not None:
        out["rows"] = int(len(df))
        out["columns"] = [str(c) for c in df.columns]
    recs = getattr(reader, "records", None)
    if isinstance(recs, list):
        out["rows"] = len(recs)
    return out


def logical_fingerprint(fp: Any) -> Any:
    """The COMPARED half of a streaming fingerprint: everything except
    the ``advisory`` section (pod process count — host counts are
    elastic, so ``pod.processCount`` must never block a resume)."""
    if isinstance(fp, dict):
        return {k: v for k, v in fp.items() if k != "advisory"}
    return fp


def compute_fingerprint(reader, raw_features, layers,
                        chunk_rows: int) -> Dict[str, Any]:
    """Identity of a streaming train: same reader bytes, same chunk
    geometry, same DAG → same pass/chunk/state sequence, so a checkpoint
    from one run is exact for the other."""
    return {
        "chunkRows": int(chunk_rows),
        "reader": _describe_reader(reader),
        "rawFeatures": sorted(f.name for f in raw_features),
        "stages": [f"{s.uid}:{type(s).__name__}:{s.get_output().name}"
                   for layer in layers for s in layer],
    }


# ---------------------------------------------------------------------------
# resume state + manager
# ---------------------------------------------------------------------------

class ResumeState:
    """Decoded checkpoint contents handed to the streaming driver."""

    def __init__(self):
        #: pass index -> {"rows": int, "models": {uid: Model}}
        self.completed: Dict[int, Dict[str, Any]] = {}
        #: in-flight pass: {"pass", "label", "chunks_done", "rows_done",
        #: "states": {uid: encoded payload}}; states decode lazily per
        #: estimator via ``states_for`` (import hooks need the estimator)
        self.current: Optional[Dict[str, Any]] = None
        #: pod manifest record ({"ranges", "processCount"}) when the
        #: checkpoint was written by a pod train; the resuming
        #: PodStreamContext adopts these ORIGINAL host entries so any
        #: process count reproduces the same per-host chunk folds
        self.pod: Optional[Dict[str, Any]] = None
        self._arrays = {}

    def decode_payload(self, raw: Any) -> Any:
        """Decode one encoded fit-state payload against this
        checkpoint's array store (the pod resume path decodes per-entry
        states lazily, one entry at a time)."""
        return decode_fit_state(raw, self._arrays)

    def states_for(self, ests: List[Estimator]) -> Dict[str, Any]:
        """Restore the in-flight states for ``ests`` through each
        estimator's ``import_fit_state`` hook."""
        raw = (self.current or {}).get("states", {})
        out = {}
        for est in ests:
            if est.uid not in raw:
                raise CheckpointMismatchError(
                    f"checkpoint mid-pass state is missing estimator "
                    f"{est.uid}")
            out[est.uid] = est.import_fit_state(
                decode_fit_state(raw[est.uid], self._arrays))
        return out


class StreamingCheckpointManager:
    """Owns ``checkpoint_dir`` for one streaming train.

    ``save_progress`` persists the in-flight pass (cursor + states) every
    call; ``complete_pass`` persists a finished pass's fitted models and
    clears the in-flight record; ``finish`` removes the checkpoint once
    the train succeeded (a stale checkpoint must not resurrect into the
    next run).  All writes are atomic (tmp + rename).
    """

    def __init__(self, directory: str, fingerprint: Dict[str, Any],
                 every_chunks: int = 16):
        if every_chunks < 1:
            raise ValueError("checkpoint every_chunks must be >= 1")
        self.directory = directory
        self.fingerprint = fingerprint
        self.every_chunks = int(every_chunks)
        self.saves = 0
        self._seq = 0
        self._completed: Dict[int, Dict[str, Any]] = {}  # manifest records
        self._current: Optional[Dict[str, Any]] = None
        #: set by the pod driver: rides on every manifest write
        self.pod_record: Optional[Dict[str, Any]] = None
        os.makedirs(directory, exist_ok=True)

    # -- resume -------------------------------------------------------------

    def load(self) -> Optional[ResumeState]:
        """The previous run's checkpoint, or None on a fresh directory.
        Also primes this manager's in-memory manifest so subsequent saves
        carry the restored passes forward."""
        path = os.path.join(self.directory, CHECKPOINT_JSON)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            doc = json.load(f)
        if doc.get("version") != CHECKPOINT_VERSION:
            raise CheckpointMismatchError(
                f"checkpoint format v{doc.get('version')} != "
                f"v{CHECKPOINT_VERSION}")
        saved_fp = doc.get("fingerprint")
        if logical_fingerprint(saved_fp) != logical_fingerprint(
                self.fingerprint):
            raise CheckpointMismatchError(_mismatch_message(
                "checkpoint", self.directory,
                logical_fingerprint(saved_fp),
                logical_fingerprint(self.fingerprint),
                "clear the directory or point checkpoint_dir elsewhere "
                "(advisory keys — pod.processCount — are NOT compared: a "
                "host-count change alone would have resumed)"))
        arrays = {}
        npz = doc.get("arrays")
        if npz:
            with np.load(os.path.join(self.directory, npz),
                         allow_pickle=True) as z:
                arrays = {k: z[k] for k in z.files}
        state = ResumeState()
        state._arrays = arrays
        for rec in doc.get("completedPasses", []):
            models = {uid: _load_stage(srec, arrays)
                      for uid, srec in rec["models"].items()}
            # final state payloads (fold-tagged CV layers persist theirs
            # so a post-pass kill still resumes the fold validation) —
            # decoded to live sketch objects, same carry-forward rule as
            # the models (raw records would dangle into superseded npz)
            payloads = {uid: decode_fit_state(p, arrays)
                        for uid, p in (rec.get("states") or {}).items()}
            state.completed[int(rec["pass"])] = {
                "rows": int(rec["rows"]), "label": rec.get("label"),
                "models": models, "states": payloads}
            self._completed[int(rec["pass"])] = {
                "pass": int(rec["pass"]), "rows": int(rec["rows"]),
                "label": rec.get("label"), "live_models": models,
                "live_payloads": payloads}
        state.current = doc.get("current")
        state.pod = doc.get("pod")
        self.pod_record = doc.get("pod") or self.pod_record
        self._seq = int(doc.get("seq", 0))
        from ..obs.flight import record_event

        record_event("checkpoint.resume", directory=self.directory,
                     seq=self._seq,
                     passes=len(state.completed))
        return state

    # -- save ---------------------------------------------------------------

    def _write(self) -> None:
        """Re-encode the manifest + arrays and land them atomically.

        Pod trains write through the COORDINATOR only (process 0) — the
        callers' save protocol is barrier-fenced around this, so every
        process observes the save as durable before proceeding (TM047
        pins the guard convention)."""
        from ..distributed.runtime import current_pod

        pod = current_pod()
        if pod.active and not pod.is_coordinator():
            return
        self._seq += 1
        store = _ArrayStore()
        doc: Dict[str, Any] = {
            "version": CHECKPOINT_VERSION,
            "fingerprint": self.fingerprint,
            "seq": self._seq,
            "completedPasses": [],
            "current": None,
        }
        # completed-pass model records re-encode against the fresh store
        # (records are small: vocabs, fills, keep-indices)
        for pi in sorted(self._completed):
            rec = self._completed[pi]
            entry = {
                "pass": pi, "rows": rec["rows"], "label": rec.get("label"),
                "models": {uid: _stage_record(m, store)
                           for uid, m in rec["live_models"].items()},
            }
            payloads = rec.get("live_payloads")
            if payloads:
                entry["states"] = {
                    uid: encode_fit_state(p, f"done{pi}.{uid}", store)
                    for uid, p in payloads.items()}
            doc["completedPasses"].append(entry)
        if self._current is not None:
            cur = dict(self._current)
            if "live_states" in cur:
                cur["states"] = {
                    uid: encode_fit_state(payload, f"cur.{uid}", store)
                    for uid, payload in cur.pop("live_states").items()}
            if "pod_live" in cur:
                # one record per ORIGINAL host: range + cursor + states
                cur["pod_entries"] = [
                    {"entry": rec["entry"], "range": rec["range"],
                     "chunks_done": rec["chunks_done"],
                     "states": {
                         uid: encode_fit_state(
                             p, f"pod{rec['entry']}.{uid}", store)
                         for uid, p in rec["states"].items()}}
                    for rec in cur.pop("pod_live")]
            doc["current"] = cur
        if self.pod_record is not None:
            doc["pod"] = self.pod_record
        npz_name = f"state-{self._seq}.npz"
        old = [n for n in sorted(os.listdir(self.directory))
               if n.startswith("state-") and n.endswith(".npz")]
        if store.arrays:
            np.savez_compressed(os.path.join(self.directory, npz_name),
                                **store.arrays)
            doc["arrays"] = npz_name
        tmp = os.path.join(self.directory, CHECKPOINT_JSON + ".tmp")
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.directory, CHECKPOINT_JSON))
        for n in old:  # previous npz generations, only after the rename
            if n != npz_name:
                try:
                    os.unlink(os.path.join(self.directory, n))
                except OSError:  # pragma: no cover
                    pass
        self.saves += 1
        from ..obs.flight import record_event

        record_event("checkpoint.save", directory=self.directory,
                     seq=self._seq, saves=self.saves)
        faults.fire("checkpoint.barrier", index=self.saves - 1)

    def save_progress(self, pass_index: int, label: str, chunks_done: int,
                      rows_done: int, ests: List[Estimator],
                      states: Dict[str, Any]) -> None:
        """Persist the in-flight pass: cursor + per-estimator states
        (through each estimator's ``export_fit_state`` hook)."""
        self._current = {
            "pass": int(pass_index), "label": label,
            "chunks_done": int(chunks_done), "rows_done": int(rows_done),
            "live_states": {est.uid: est.export_fit_state(states[est.uid])
                            for est in ests},
        }
        self._write()

    def save_progress_pod(self, pass_index: int, label: str,
                          entries: List[Dict[str, Any]],
                          rows_done: int = 0) -> None:
        """Pod variant of :meth:`save_progress`: one record PER ORIGINAL
        HOST ({entry, range, chunks_done, states} — states already
        exported payloads, gathered from every process).  Called on the
        coordinator only, inside the barrier-fenced pod save step."""
        self._current = {
            "pass": int(pass_index), "label": label,
            "rows_done": int(rows_done),
            "pod_live": [dict(rec) for rec in entries],
        }
        self._write()

    def complete_pass(self, pass_index: int, label: str, rows: int,
                      models: Dict[str, Model],
                      state_payloads: Optional[Dict[str, Any]] = None
                      ) -> None:
        """Persist a finished pass's fitted models; clears the in-flight
        record (the cursor is meaningless once the pass is done).

        ``state_payloads`` (uid -> ``export_fit_state`` payload) rides
        along for estimators whose FINAL state is still needed after the
        pass — the fold-tagged CV layers: a kill after the pass but
        before the fold validation must restore the per-fold states, not
        just the full-data model."""
        self._completed[int(pass_index)] = {
            "pass": int(pass_index), "label": label, "rows": int(rows),
            "live_models": models,
            "live_payloads": dict(state_payloads or {}),
        }
        self._current = None
        self._write()

    def finish(self) -> None:
        """The train succeeded: remove the checkpoint so a later run in the
        same directory starts fresh instead of resuming a finished fit."""
        from ..distributed.runtime import current_pod

        pod = current_pod()
        if pod.active and not pod.is_coordinator():
            return
        for n in (CHECKPOINT_JSON, CHECKPOINT_JSON + ".tmp"):
            try:
                os.unlink(os.path.join(self.directory, n))
            except OSError:
                pass
        for n in sorted(os.listdir(self.directory)):
            if n.startswith("state-") and n.endswith(".npz"):
                try:
                    os.unlink(os.path.join(self.directory, n))
                except OSError:  # pragma: no cover
                    pass


# ---------------------------------------------------------------------------
# pod-striped block-pass checkpoints (ROADMAP item 3: the 10M-row plane)
# ---------------------------------------------------------------------------

class BlockStripeStore:
    """Per-host checkpoint stripes for one block-streaming pass.

    The block plane (distributed/podstream.py) folds a host's row blocks
    through device-resident accumulators; its durable progress is just
    {pass label, blocks folded, accumulator arrays} — the per-host record
    format of the pod mid-pass protocol, striped: EACH host persists ONLY
    its own cursor + partials to its own ``blocks.p<i>.npz``, so a resume
    reads one stripe sized by the host's shard, never the whole pod's —
    resume wall scales with per-host shard size, not total rows.

    TM047 (coordinator-only durable writes) governs SHARED artifacts; a
    stripe is process-private by construction — the filename carries the
    process index, exactly like the per-process flight dumps — so every
    host writing its own stripe is the point, not a violation.  Writes
    are atomic (tmp + ``os.replace`` + fsync) and fire the
    ``blockplane.checkpoint`` fault point after landing, the hook the
    SIGKILL-resume bench kills at.
    """

    def __init__(self, directory: str, process_index: int):
        self.directory = directory
        self.process_index = int(process_index)
        self.saves = 0
        os.makedirs(directory, exist_ok=True)

    def _path(self) -> str:
        return os.path.join(self.directory,
                            f"blocks.p{self.process_index}.npz")

    def save(self, label: str, blocks_done: int,
             accs: Dict[str, np.ndarray],
             meta: Optional[Dict[str, Any]] = None) -> None:
        """Persist this host's pass cursor + partial accumulators
        (bit-exact npz round trip — blocked folds resume mid-pass)."""
        payload = {f"acc_{k}": np.asarray(v) for k, v in accs.items()}
        payload["__meta__"] = np.frombuffer(json.dumps({
            "label": str(label), "blocksDone": int(blocks_done),
            "process": self.process_index, "meta": meta or {},
        }).encode("utf-8"), dtype=np.uint8)
        tmp = self._path() + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path())
        self.saves += 1
        from ..obs.flight import record_event

        record_event("checkpoint.save", directory=self.directory,
                     saves=self.saves, stripe=self.process_index,
                     blocks=int(blocks_done), blockplane=True)
        faults.fire("blockplane.checkpoint", index=self.saves - 1)

    def load(self, label: str) -> Optional[Dict[str, Any]]:
        """This host's stripe for ``label``, or None (fresh pass / stripe
        belongs to a different pass).  Returns ``{"blocksDone", "accs",
        "meta"}`` with accumulators restored bit-exactly."""
        path = self._path()
        if not os.path.exists(path):
            return None
        try:
            with np.load(path, allow_pickle=False) as z:
                head = json.loads(bytes(z["__meta__"]).decode("utf-8"))
                if head.get("label") != str(label):
                    return None
                accs = {k[len("acc_"):]: z[k] for k in z.files
                        if k.startswith("acc_")}
        except (OSError, ValueError, KeyError):
            return None
        return {"blocksDone": int(head.get("blocksDone", 0)),
                "accs": accs, "meta": head.get("meta") or {}}

    def clear(self) -> None:
        """The pass completed: drop THIS host's stripe (each host clears
        its own — no coordinator funnel, same striping as the saves)."""
        for suffix in ("", ".tmp"):
            try:
                os.unlink(self._path() + suffix)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# mid-sweep cursor: selector-sweep checkpoint/resume (ROADMAP item 1)
# ---------------------------------------------------------------------------

def mesh_record(mesh) -> Optional[Dict[str, Any]]:
    """The ADVISORY mesh record a sweep checkpoint carries: the shape the
    sweep was running on when it saved, plus the device count.  Never
    compared on resume — recorded so the resuming process can see (and
    count, ``ElasticContext.note_resumed_mesh``) that it re-batched the
    remaining units onto a different mesh."""
    if mesh is None:
        return None
    shape = {name: int(mesh.shape[name]) for name in mesh.axis_names}
    n = 1
    for v in shape.values():
        n *= v
    return {"shape": shape, "devices": n}


def sweep_fingerprint(candidates, metric_name: str, validator_desc: str,
                      mesh=None, strategy: str = "full",
                      n_rows: int = 0) -> Dict[str, Any]:
    """Identity of one selector sweep, split in two:

    ``logical`` — candidate list (names + identity params in order),
    validator geometry, metric, strategy, row count.  Same logical
    identity → same unit sequence and the same per-unit fold metrics (the
    durable records are HOST floats, computed identically on any mesh up
    to the documented 2e-2 sharded tolerance), so a cursor from one run
    is exact for the other — this half is COMPARED on resume.

    ``mesh`` — the advisory record of the mesh the sweep ran on
    (:func:`mesh_record`).  Deliberately NOT part of the compared
    identity: TPU fleets are preemptible and resize under you, and the
    sweep's remaining units re-batch onto whatever mesh the resuming
    process has (the grid-group packing is rebuilt per process/rung).
    """
    return {
        "logical": {
            "candidates": [[str(c[0]), json.dumps(c[1], sort_keys=True,
                                                  default=str)]
                           for c in candidates],
            "metric": metric_name,
            "validator": validator_desc,
            "strategy": strategy,
            "nRows": int(n_rows),
        },
        "mesh": mesh_record(mesh),
    }


class SweepCheckpointManager:
    """Owns the mid-sweep cursor for ONE selector sweep.

    The durable unit is a completed :class:`~transmogrifai_tpu.selector.
    validators.SweepUnit`'s fold metrics (host floats — recorded after the
    unit's stacked device fetch) plus, for successive halving, the rung
    state (alive set, per-candidate last results, elimination records).
    Saves are atomic (``utils.jsonio.write_json_atomic``: tmp +
    ``os.replace``) every ``every_units`` records, and at every rung
    boundary; a SIGKILL at any byte leaves the previous cursor intact.

    ``scoped(tag)`` returns a view namespacing unit indices (the halving
    scheduler runs each rung through a fresh queue whose local indices
    would otherwise collide across rungs).
    """

    def __init__(self, directory: str, fingerprint: Dict[str, Any],
                 every_units: int = 1):
        if every_units < 1:
            raise ValueError("sweep checkpoint every_units must be >= 1")
        self.directory = directory
        self.fingerprint = fingerprint
        self.every_units = int(every_units)
        self.saves = 0
        self._units: Dict[str, Dict[str, Any]] = {}
        self._rung: Optional[Dict[str, Any]] = None
        self._dirty = 0
        #: advisory mesh record the loaded checkpoint was saved under
        #: (None until load(); may differ from the current fingerprint's
        #: mesh — that is the ELASTIC resume case, not a mismatch)
        self.resumed_mesh: Optional[Dict[str, Any]] = None
        self.mesh_changed = False
        os.makedirs(directory, exist_ok=True)

    # -- resume -------------------------------------------------------------

    def load(self) -> bool:
        """Prime the cursor from disk; True when a checkpoint was found.

        Only the LOGICAL half of the fingerprint is compared — a sweep
        checkpointed on one mesh shape resumes on any other (the durable
        unit records are host fold metrics, mesh-independent), with the
        saved advisory mesh surfaced as ``resumed_mesh``/``mesh_changed``
        so the caller can count the re-pack.  A logical mismatch raises
        :class:`CheckpointMismatchError` with the key-level diff
        (refusing to resume beats silently blending two sweeps)."""
        path = os.path.join(self.directory, SWEEP_CHECKPOINT_JSON)
        if not os.path.exists(path):
            return False
        with open(path) as f:
            doc = json.load(f)
        if doc.get("version") != SWEEP_CHECKPOINT_VERSION:
            raise CheckpointMismatchError(
                f"sweep checkpoint format v{doc.get('version')} != "
                f"v{SWEEP_CHECKPOINT_VERSION}")
        saved = doc.get("fingerprint") or {}
        if saved.get("logical") != self.fingerprint.get("logical"):
            raise CheckpointMismatchError(_mismatch_message(
                "sweep checkpoint", self.directory,
                saved.get("logical"), self.fingerprint.get("logical"),
                "the LOGICAL sweep identity (candidates/validator/metric/"
                "strategy) changed — clear the directory or point the "
                "checkpoint elsewhere (a mesh-shape change alone would "
                "have resumed)"))
        self.resumed_mesh = saved.get("mesh")
        self.mesh_changed = saved.get("mesh") != self.fingerprint.get("mesh")
        self._units = dict(doc.get("units", {}))
        self._rung = doc.get("rung")
        from ..obs.flight import record_event

        record_event("checkpoint.resume", directory=self.directory,
                     units=len(self._units),
                     mesh_changed=self.mesh_changed, sweep=True)
        return True

    # -- unit cursor --------------------------------------------------------

    def restore(self, index: int, tag: str = ""):
        rec = self._units.get(f"{tag}{index}")
        if rec is None:
            return None
        return list(rec.get("foldValues", [])), rec.get("error")

    def record_unit(self, index: int, fold_vals, error: Optional[str],
                    tag: str = "") -> None:
        self._units[f"{tag}{index}"] = {
            "foldValues": [float(v) for v in fold_vals],
            "error": error}
        self._dirty += 1
        if self._dirty >= self.every_units:
            self._write()

    # -- halving rung state -------------------------------------------------

    def rung_state(self) -> Optional[Dict[str, Any]]:
        return self._rung

    def save_rung_state(self, state: Dict[str, Any]) -> None:
        self._rung = state
        self._write()

    # -- plumbing -----------------------------------------------------------

    def export_doc(self) -> Dict[str, Any]:
        """The manifest exactly as ``_write`` persists it — the export
        half of the TM026 fingerprint round-trip contract
        (``analysis/contracts.check_checkpoint_roundtrip``): a manager
        primed by ``load()`` must re-export the bytes it read."""
        return {"version": SWEEP_CHECKPOINT_VERSION,
                "fingerprint": self.fingerprint,
                "units": self._units,
                "rung": self._rung}

    def _write(self) -> None:
        from ..distributed.runtime import current_pod
        from ..utils.jsonio import write_json_atomic

        pod = current_pod()
        if pod.active and not pod.is_coordinator():
            # the sweep replicates deterministically on every pod process;
            # its durable cursor is the coordinator's to write (TM047)
            self._dirty = 0
            return
        write_json_atomic(
            os.path.join(self.directory, SWEEP_CHECKPOINT_JSON),
            self.export_doc())
        self._dirty = 0
        self.saves += 1
        from ..obs.flight import record_event

        record_event("checkpoint.save", directory=self.directory,
                     saves=self.saves, units=len(self._units), sweep=True)
        faults.fire("sweep.checkpoint", index=self.saves - 1)

    def flush(self) -> None:
        if self._dirty:
            self._write()

    def sync_durability(self, name: str = "sweep.final") -> None:
        """Barrier-fence the sweep cursor's FINAL durable sync.

        ``_write`` is coordinator-only (TM047's first half); under PR
        17's async dispatch the closing ``flush_pending(overlapped=
        False)`` is the last write of the sweep, and without a fence a
        non-coordinator could run past it — and be SIGKILLed, or start
        consuming the winner — before the coordinator's cursor landed on
        disk (TM047's second half: every process observes the save as
        durable before proceeding).  Called by the async scheduler right
        after its final flush; a no-op outside a pod."""
        from ..distributed.runtime import current_pod

        pod = current_pod()
        if pod.active:
            pod.barrier(name)

    def scoped(self, tag: str) -> "_ScopedSweepCheckpoint":
        return _ScopedSweepCheckpoint(self, f"{tag}:")

    def finish(self) -> None:
        """The sweep completed: remove the cursor so a later sweep in the
        same directory starts fresh.  Coordinator-only unlink, fenced by
        a barrier so no process outlives the sweep believing a stale
        cursor is still on disk (the same fence-after-durable-effect
        rule as the streaming manager's pass saves)."""
        from ..distributed.runtime import current_pod

        pod = current_pod()
        if not pod.active or pod.is_coordinator():
            try:
                os.unlink(os.path.join(self.directory,
                                       SWEEP_CHECKPOINT_JSON))
            except OSError:
                pass
        if pod.active:
            pod.barrier("sweep.finish")


class _ScopedSweepCheckpoint:
    """Namespace view over a SweepCheckpointManager (per-rung cursors)."""

    def __init__(self, manager: SweepCheckpointManager, tag: str):
        self._m = manager
        self._tag = tag

    def restore(self, index: int):
        return self._m.restore(index, tag=self._tag)

    def record_unit(self, index: int, fold_vals,
                    error: Optional[str]) -> None:
        self._m.record_unit(index, fold_vals, error, tag=self._tag)

    def flush(self) -> None:
        self._m.flush()

    def sync_durability(self, name: str = "sweep.final") -> None:
        self._m.sync_durability(name)


def adopt_restored_model(est: Estimator, model: PipelineStage) -> Model:
    """Wire a checkpoint-restored model to answer for ``est`` in the live
    DAG — the resume analogue of ``Estimator.adopt_model``, except the
    restored model's fitted METADATA is authoritative (the estimator never
    ran in this process, so its metadata dict is empty)."""
    model.uid = est.uid
    model.operation_name = est.operation_name
    model.input_features = list(est.input_features)
    model._output_feature = est._output_feature
    est.metadata = model.metadata  # summaries travel with the fit
    return model
