"""Feature-DAG computation and layered execution.

Reference: ``FitStagesUtil`` (core/.../utils/stages/FitStagesUtil.scala:173,212-300):
``computeDAG`` layers the stage DAG topologically; ``fitAndTransformDAG``
iterates layers, fitting estimators then bulk-applying the layer's
transformers.

TPU note: the reference bulk-applies each layer's row-UDFs as one Spark
``select``; here each layer's columnar transforms run vectorized and the
device-heavy stages (vectorizers/models) are jitted internally, so XLA does
the fusion the reference got from Catalyst.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..features.feature import Feature, FeatureCycleError
from ..stages.base import Estimator, Model, PipelineStage, Transformer
from ..stages.generator import FeatureGeneratorStage
from ..types.columns import ColumnarDataset

__all__ = ["StagesDAG", "compute_dag", "fit_and_transform_dag", "transform_dag",
           "CutDAG", "cut_dag_cv"]

#: operational kill-switch: set to "1" to revert every DAG execution to the
#: pre-plan strictly-sequential executor (no pruning, eager apply_to, full
#: per-fold column gathers).  Also the honest A/B lever for
#: examples/bench_pipeline.py.
SEQUENTIAL_EXECUTOR_ENV = "TMOG_SEQUENTIAL_EXECUTOR"


def sequential_executor_forced() -> bool:
    return os.environ.get(SEQUENTIAL_EXECUTOR_ENV) == "1"


class StagesDAG:
    """Layers of stages, topologically ordered (layer 0 first = raw generators)."""

    def __init__(self, layers: List[List[PipelineStage]]):
        self.layers = layers

    def all_stages(self) -> List[PipelineStage]:
        return [s for layer in self.layers for s in layer]

    def non_generator_layers(self) -> List[List[PipelineStage]]:
        return [
            [s for s in layer if not isinstance(s, FeatureGeneratorStage)]
            for layer in self.layers
        ]

    def __repr__(self):
        return f"StagesDAG({[len(l) for l in self.layers]} stages/layer)"


def compute_dag(result_features: Sequence[Feature]) -> StagesDAG:
    """Reconstruct + layer the stage DAG from result features.

    Port of FitStagesUtil.computeDAG (FitStagesUtil.scala:173): stages are
    grouped into layers by longest path from the raw generators, so every
    stage appears after all its input producers.
    """
    # collect all stages reachable from result features (cycle-checked)
    stages: Dict[str, PipelineStage] = {}

    for rf in result_features:
        def visit(f: Feature):
            s = f.origin_stage
            if s is None:
                raise ValueError(f"feature {f.name!r} has no origin stage")
            stages[s.uid] = s

        rf.traverse(visit)

    # stage dependency edges: stage -> stages producing its inputs
    depth: Dict[str, int] = {}

    def stage_depth(s: PipelineStage, on_path: Tuple[str, ...] = ()) -> int:
        if s.uid in depth:
            return depth[s.uid]
        if s.uid in on_path:
            raise FeatureCycleError(f"cycle through stage {s.uid}")
        if not s.input_features:
            d = 0
        else:
            d = 0
            for f in s.input_features:
                p = f.origin_stage
                if p is None:
                    continue
                stages.setdefault(p.uid, p)
                d = max(d, 1 + stage_depth(p, on_path + (s.uid,)))
        depth[s.uid] = d
        return d

    for s in list(stages.values()):
        stage_depth(s)

    n_layers = max(depth.values()) + 1 if depth else 0
    layers: List[List[PipelineStage]] = [[] for _ in range(n_layers)]
    # stable order: by first-seen insertion
    for uid, s in stages.items():
        layers[depth[uid]].append(s)
    return StagesDAG(layers)


def fit_and_transform_dag(
    dag: StagesDAG,
    train: ColumnarDataset,
    apply_to: Optional[ColumnarDataset] = None,
    fitted_substitutes: Optional[Dict[str, Model]] = None,
    keep: Optional[Sequence[str]] = None,
    profiler=None,
    sequential: Optional[bool] = None,
) -> Tuple[List[PipelineStage], ColumnarDataset, Optional[ColumnarDataset]]:
    """Fit estimators layer by layer, transforming as we go.

    Port of FitStagesUtil.fitAndTransformDAG/fitAndTransformLayer
    (FitStagesUtil.scala:212-300).  Returns (fitted stages in topo order,
    transformed train data, transformed ``apply_to`` data or None — the
    reference's FittedDAG(trainData, testData, transformers)).
    ``fitted_substitutes`` allows warm-start (OpWorkflow.withModelStages
    parity): estimators whose uid appears there are skipped and the fitted
    model used directly.

    Execution goes through the memoized ``ExecutionPlan`` (workflow/plan.py):
    liveness pruning when ``keep`` names the columns the caller needs
    (``keep=None`` retains every intermediate, the historical behavior),
    intra-layer host parallelism, lazy plan-driven ``apply_to``, and
    per-stage profiling into ``profiler`` (a ``PlanProfiler``).
    ``sequential=True`` forces the plain stage-by-stage loop — the
    pre-plan executor, kept for determinism tests and benchmarks
    (``TMOG_SEQUENTIAL_EXECUTOR=1`` forces it process-wide).
    """
    if sequential is None:
        sequential = sequential_executor_forced()
    if sequential:
        return _fit_and_transform_sequential(
            dag, train, apply_to, fitted_substitutes)
    from .plan import plan_for

    return plan_for(dag, keep=keep).fit_and_transform(
        train, apply_to=apply_to, fitted_substitutes=fitted_substitutes,
        profiler=profiler)


def _fit_and_transform_sequential(
    dag: StagesDAG,
    train: ColumnarDataset,
    apply_to: Optional[ColumnarDataset] = None,
    fitted_substitutes: Optional[Dict[str, Model]] = None,
) -> Tuple[List[PipelineStage], ColumnarDataset, Optional[ColumnarDataset]]:
    """The pre-plan executor: strictly sequential, eager ``apply_to``, no
    pruning.  The determinism baseline the plan executor is asserted
    byte-identical against (tests/test_plan_executor.py) and the
    comparison executor for ``examples/bench_pipeline.py``."""
    fitted_substitutes = fitted_substitutes or {}
    fitted: List[PipelineStage] = []
    data = train
    for layer in dag.non_generator_layers():
        for stage in layer:
            if isinstance(stage, Estimator):
                model = fitted_substitutes.get(stage.uid) or stage.fit(data)
                fitted.append(model)
                data = model.transform(data)
                if apply_to is not None:
                    apply_to = model.transform(apply_to)
            elif isinstance(stage, Transformer):
                fitted.append(stage)
                data = stage.transform(data)
                if apply_to is not None:
                    apply_to = stage.transform(apply_to)
            else:
                raise TypeError(f"cannot execute stage {stage!r}")
    return fitted, data, apply_to


def transform_dag(
    dag: StagesDAG, data: ColumnarDataset,
    up_to_feature: Optional[str] = None,
    keep: Optional[Sequence[str]] = None,
    profiler=None,
) -> ColumnarDataset:
    """Apply an already-fitted DAG (scoring path; OpWorkflowCore.applyTransformationsDAG).

    ``up_to_feature`` stops once the named feature is materialized
    (OpWorkflow.computeDataUpTo parity) and keeps the historical
    sequential semantics (every stage before the stopping point runs).
    Otherwise execution reuses the DAG's memoized ExecutionPlan — the same
    pruned plan serving/scoring callers share — with ``keep`` bounding the
    resident columns.
    """
    if up_to_feature is not None or sequential_executor_forced():
        for layer in dag.non_generator_layers():
            for stage in layer:
                if isinstance(stage, Estimator):
                    raise RuntimeError(
                        f"unfitted estimator {stage.uid} in scoring DAG"
                    )
                data = stage.transform(data)
                if up_to_feature is not None and up_to_feature in data:
                    return data
        return data
    from .plan import plan_for

    return plan_for(dag, keep=keep).transform(data, profiler=profiler)


@dataclasses.dataclass
class CutDAG:
    """The DAG split for workflow-level CV (FitStagesUtil.CutDAG parity):
    ``before`` fits once on the full training data (leakage-free stages),
    ``during`` refits inside every CV fold, ``after`` fits after the
    selector has chosen its model."""

    selector: Optional[PipelineStage]
    before: StagesDAG
    during: StagesDAG
    after: StagesDAG


def cut_dag_cv(dag: StagesDAG) -> CutDAG:
    """Split the DAG at the ModelSelector for workflow-level CV.

    Port of FitStagesUtil.cutDAG (FitStagesUtil.scala:302-355).  The
    reference's rule: within the selector's ancestor DAG, the first layer
    containing a stage whose inputs mix response and predictor features
    (a potential label-leaking estimator, e.g. SanityChecker or a supervised
    bucketizer) marks the start of the "during" DAG — those stages must be
    refit inside each fold.  Everything upstream of that point is "before";
    stages that do not feed the selector are "after".  At most one
    ModelSelector is allowed in a workflow.
    """
    from ..selector.model_selector import ModelSelector

    selectors = [s for layer in dag.layers for s in layer
                 if isinstance(s, ModelSelector)]
    if not selectors:
        return CutDAG(None, StagesDAG([]), StagesDAG([]), dag)
    if len(selectors) > 1:
        raise ValueError(
            f"workflow can contain at most 1 ModelSelector, found "
            f"{len(selectors)}: {[s.uid for s in selectors]}")
    selector = selectors[0]

    ancestors: Set[str] = set()

    def collect(s: PipelineStage):
        for f in s.input_features:
            p = f.origin_stage
            if p is not None and p.uid not in ancestors:
                ancestors.add(p.uid)
                collect(p)

    collect(selector)

    def mixes_response(s: PipelineStage) -> bool:
        ins = s.input_features
        return (any(f.is_response for f in ins)
                and any(not f.is_response for f in ins))

    # ancestor layers in topological order
    anc_layers = [[s for s in layer if s.uid in ancestors]
                  for layer in dag.layers]
    anc_layers = [l for l in anc_layers if l]
    first_cv = next((i for i, layer in enumerate(anc_layers)
                     if any(mixes_response(s) for s in layer)), None)
    if first_cv is None:
        before_layers, during_layers = anc_layers, []
    else:
        before_layers = anc_layers[:first_cv]
        during_layers = anc_layers[first_cv:]

    after_layers = [[s for s in layer
                     if s.uid not in ancestors and s is not selector]
                    for layer in dag.layers]
    after_layers = [l for l in after_layers if l]
    return CutDAG(selector, StagesDAG(before_layers),
                  StagesDAG(during_layers), StagesDAG(after_layers))
