"""Workflow-model persistence — one JSON artifact + one array bundle.

Reference: ``OpWorkflowModelWriter.toJson`` writes a single ``op-model.json``
holding the feature-DAG JSON and per-stage JSON (ctor args recovered by
reflection, ``DefaultOpPipelineStageReaderWriter``), with Spark/MLeap model
binaries saved beside it (OpWorkflowModelWriter.scala:54-150,
OpPipelineStageReaderWriter.scala); ``OpWorkflowModelReader`` reconstructs
stages → features → model (OpWorkflowModelReader.scala).

TPU-native layout (directory):
  op-model.json   — version, result features, feature DAG, stage records
  arrays.npz      — every ndarray-valued stage param, keyed "<uid>.<param>"

Stage record = dotted class path + JSON params (arrays externalized, nested
stages recursed, feature-type classes by name) + ``extra_state`` hook payload
+ fitted metadata.  Stages reconstruct by calling their constructor with the
round-tripped kwargs — the same ctor-args contract the reference enforces.
"""
from __future__ import annotations

import importlib
import json
import os
import shutil
import warnings
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..features.feature import Feature
from ..ops.vector_metadata import VectorMetadata
from ..stages.base import PipelineStage
from ..stages.generator import FeatureGeneratorStage
from ..types.feature_types import FeatureType, type_by_name

__all__ = ["save_workflow_model", "load_workflow_model", "MODEL_JSON",
           "FORMAT_VERSION", "check_serializable"]

MODEL_JSON = "op-model.json"
ARRAYS_NPZ = "arrays.npz"
FORMAT_VERSION = 1

try:  # jax arrays serialize like numpy
    import jax

    _ARRAY_TYPES: Tuple[type, ...] = (np.ndarray, jax.Array)
except Exception:  # pragma: no cover
    _ARRAY_TYPES = (np.ndarray,)


# ---------------------------------------------------------------------------
# encoding
# ---------------------------------------------------------------------------

class _ArrayStore:
    def __init__(self):
        self.arrays: Dict[str, np.ndarray] = {}

    def put(self, key: str, arr) -> Dict[str, str]:
        k = key
        i = 0
        while k in self.arrays:
            i += 1
            k = f"{key}#{i}"
        self.arrays[k] = np.asarray(arr)
        return {"__array__": k}


def _encode(value: Any, key: str, store: _ArrayStore) -> Any:
    if isinstance(value, _ARRAY_TYPES):
        return store.put(key, value)
    if isinstance(value, (np.integer,)):
        return int(value)
    if isinstance(value, (np.floating,)):
        return float(value)
    if isinstance(value, np.bool_):
        return bool(value)
    if isinstance(value, PipelineStage):
        return {"__stage__": _stage_record(value, store)}
    if isinstance(value, VectorMetadata):
        return {"__vmeta__": value.to_json()}
    if isinstance(value, type) and issubclass(value, FeatureType):
        return {"__ftype__": value.type_name()}
    if isinstance(value, dict):
        return {k: _encode(v, f"{key}.{k}", store) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_encode(v, f"{key}[{i}]", store) for i, v in enumerate(value)]
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if callable(value):
        # extract lambdas etc. — not serializable (the reference captures
        # macro source; we fall back to by-name extraction on load).
        # Warn at save time: the stage will reconstruct with fn=None.
        warnings.warn(
            f"non-serializable callable at {key!r} saved as a stub; the "
            f"loaded stage falls back to default behavior (by-name column "
            f"extraction) or fails if the callable is required",
            stacklevel=2)
        return {"__callable__": getattr(value, "__name__", "<fn>")}
    return {"__repr__": repr(value)}


def _find_unserializable(value: Any, path: str, out: List[str]) -> None:
    """Collect param paths whose values ``_encode`` would stub (callables).

    Mirrors ``_encode``'s dispatch order — feature-type classes, stages,
    arrays etc. all round-trip and are skipped."""
    if isinstance(value, _ARRAY_TYPES) or value is None \
            or isinstance(value, (bool, int, float, str, np.generic)):
        return
    if isinstance(value, PipelineStage):
        for n in _find_unserializable_stage(value):
            out.append(f"{path}.{n}")
        return
    if isinstance(value, VectorMetadata):
        return
    if isinstance(value, type) and issubclass(value, FeatureType):
        return
    if isinstance(value, dict):
        for k, v in value.items():
            _find_unserializable(v, f"{path}.{k}", out)
        return
    if isinstance(value, (list, tuple)):
        for i, v in enumerate(value):
            _find_unserializable(v, f"{path}[{i}]", out)
        return
    if callable(value):
        out.append(path)


def _find_unserializable_stage(stage: PipelineStage) -> List[str]:
    out: List[str] = []
    for name, value in stage.get_params().items():
        _find_unserializable(value, name, out)
    return out


def check_serializable(stages) -> None:
    """Train-time serializability gate (``OpWorkflow.checkSerializable``,
    OpWorkflow.scala:280): fail FAST — naming the stage and param — when a
    stage parameter would not survive a save/load round trip, instead of
    silently stubbing it at save time (a model trained from
    lambda-extracted features would otherwise lose its extractors on load;
    raw features are covered through their generator stages in the DAG).
    Named module-level functions do not round-trip either (the persistence
    format records ctor kwargs, not code), so the remedy is by-name
    extraction (extract_fn=None) or ``OpWorkflow.allow_non_serializable()``.
    """
    problems: List[str] = []
    for s in stages:
        for p in _find_unserializable_stage(s):
            problems.append(f"stage {type(s).__name__}[{s.uid}] param {p!r}")
    if problems:
        raise ValueError(
            "workflow contains state that cannot survive a save/load round "
            "trip:\n  - " + "\n  - ".join(problems) +
            "\nUse by-name extraction / serializable params, or opt out "
            "with OpWorkflow.allow_non_serializable() to train anyway "
            "(saving will stub these values).")


def _decode(value: Any, arrays) -> Any:
    if isinstance(value, dict):
        if "__array__" in value:
            return arrays[value["__array__"]]
        if "__stage__" in value:
            return _load_stage(value["__stage__"], arrays)
        if "__vmeta__" in value:
            return VectorMetadata.from_json(value["__vmeta__"])
        if "__ftype__" in value:
            return type_by_name(value["__ftype__"])
        if "__callable__" in value or "__repr__" in value:
            return None
        return {k: _decode(v, arrays) for k, v in value.items()}
    if isinstance(value, list):
        return [_decode(v, arrays) for v in value]
    return value


def _stage_record(stage: PipelineStage, store: _ArrayStore) -> Dict[str, Any]:
    params = {
        k: _encode(v, f"{stage.uid}.{k}", store)
        for k, v in stage.get_params().items()
    }
    rec: Dict[str, Any] = {
        "className": f"{type(stage).__module__}.{type(stage).__qualname__}",
        "uid": stage.uid,
        "operationName": stage.operation_name,
        "outputType": stage.output_type.type_name() if stage.output_type else None,
        "params": params,
        "inputFeatures": [f.name for f in stage.input_features],
        "outputFeature": (stage._output_feature.name
                          if stage._output_feature else None),
    }
    extra = stage.extra_state()
    if extra:
        rec["extraState"] = {
            k: _encode(v, f"{stage.uid}.extra.{k}", store)
            for k, v in extra.items()
        }
    if stage.metadata:
        rec["metadata"] = _encode(stage.metadata, f"{stage.uid}.meta", store)
    return rec


def _load_stage(rec: Dict[str, Any], arrays) -> PipelineStage:
    import inspect

    mod_name, _, cls_name = rec["className"].rpartition(".")
    cls = getattr(importlib.import_module(mod_name), cls_name)
    params = {k: _decode(v, arrays) for k, v in rec["params"].items()}
    params["uid"] = rec["uid"]
    # required ctor args excluded from get_params (e.g. LambdaTransformer's
    # output_type) — recovered from the record where possible
    sig = inspect.signature(cls.__init__)
    if ("output_type" in sig.parameters and "output_type" not in params
            and rec.get("outputType")):
        params["output_type"] = type_by_name(rec["outputType"])
    if ("operation_name" in sig.parameters and "operation_name" not in params
            and rec.get("operationName")):
        params["operation_name"] = rec["operationName"]
    stage = cls(**params)
    stage.operation_name = rec.get("operationName", stage.operation_name)
    if rec.get("extraState"):
        stage.set_extra_state(
            {k: _decode(v, arrays) for k, v in rec["extraState"].items()})
    if rec.get("metadata"):
        stage.metadata = _decode(rec["metadata"], arrays)
    return stage


# ---------------------------------------------------------------------------
# save
# ---------------------------------------------------------------------------

def save_workflow_model(model, path: str, overwrite: bool = True) -> None:
    from .workflow import OpWorkflowModel  # cycle guard

    if os.path.exists(path):
        if not overwrite:
            raise FileExistsError(path)
        if os.path.isdir(path):
            shutil.rmtree(path)
        else:
            os.remove(path)
    os.makedirs(path)

    store = _ArrayStore()
    # stages in scoring-DAG order: raw generators first, then fitted stages
    dag = model._scoring_dag()
    gen_stages: List[FeatureGeneratorStage] = [
        f.origin_stage for f in model.raw_features()
        if isinstance(f.origin_stage, FeatureGeneratorStage)
    ]
    stage_records = [_stage_record(s, store) for s in gen_stages]
    for layer in dag.layers:
        for s in layer:
            if not isinstance(s, FeatureGeneratorStage):
                stage_records.append(_stage_record(s, store))

    from ..utils.version import version_info

    rff = model.raw_feature_filter_results
    doc = {
        "version": FORMAT_VERSION,
        "versionInfo": version_info().to_json(),
        "resultFeatures": [f.name for f in model.result_features],
        "stages": stage_records,
        # structured results persist via their own JSON form; loaded models
        # carry the dict (consumers accept either — see model_insights)
        "rawFeatureFilterResults": (rff.to_json() if hasattr(rff, "to_json")
                                    else rff),
    }
    fit_states = getattr(model, "fit_states", None)
    if fit_states:
        # exported streaming fit states (the warm-start capital a later
        # OpWorkflow.refresh merges new data into) persist through the
        # checkpoint codec — sketches via to_state hooks, ndarrays into
        # the same arrays.npz store as the stage params
        from .checkpoint import encode_fit_state

        doc["fitStates"] = {
            uid: encode_fit_state(payload, f"fitstate.{uid}", store)
            for uid, payload in fit_states.items()}
    from ..utils.jsonio import write_json_atomic

    # atomic (tmp + os.replace): a kill mid-save can never leave a
    # truncated model.json next to a complete arrays.npz (TM050)
    write_json_atomic(os.path.join(path, MODEL_JSON), doc, indent=2)
    np.savez_compressed(os.path.join(path, ARRAYS_NPZ), **store.arrays)


# ---------------------------------------------------------------------------
# load
# ---------------------------------------------------------------------------

def load_workflow_model(path: str):
    from .workflow import OpWorkflowModel  # cycle guard

    with open(os.path.join(path, MODEL_JSON)) as f:
        doc = json.load(f)
    if doc.get("version", 0) > FORMAT_VERSION:  # pragma: no cover
        warnings.warn(f"model format v{doc['version']} newer than v{FORMAT_VERSION}")
    npz_path = os.path.join(path, ARRAYS_NPZ)
    arrays = np.load(npz_path, allow_pickle=False) if os.path.exists(npz_path) else {}

    features: Dict[str, Feature] = {}
    stages: List[PipelineStage] = []
    for rec in doc["stages"]:
        stage = _load_stage(rec, arrays)
        if isinstance(stage, FeatureGeneratorStage):
            features[stage.name] = stage.get_output()
        else:
            parents = [features[n] for n in rec["inputFeatures"]]
            stage.set_input(*parents)
            out = stage.get_output()
            saved_name = rec.get("outputFeature")
            if saved_name and saved_name != out.name:
                out.name = saved_name
            features[out.name] = out
            stages.append(stage)

    result = [features[n] for n in doc["resultFeatures"]]
    model = OpWorkflowModel(result_features=result, stages=stages)
    model.raw_feature_filter_results = doc.get("rawFeatureFilterResults")
    if doc.get("fitStates"):
        from .checkpoint import decode_fit_state

        model.fit_states = {
            uid: decode_fit_state(rec, arrays)
            for uid, rec in doc["fitStates"].items()}
    return model
