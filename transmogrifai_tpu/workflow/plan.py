"""Execution planning for the feature DAG — liveness, COW, layer parallelism.

The reference gets intra-layer fusion and column pruning for free from
Spark's Catalyst optimizer: ``FitStagesUtil.fitAndTransformLayer`` bulk-
applies each layer as one ``select`` and the unused columns never
materialize.  This module is the TPU port's equivalent, computed ONCE per
DAG and memoized on it:

* **Column liveness** — every DAG column's last consumer layer is known
  statically, so each intermediate is dropped from the dataset immediately
  after that layer, bounding peak host/device memory instead of
  accumulating every intermediate for the whole run.  Pruning only engages
  when the caller states what it needs (``keep``); with ``keep=None`` the
  executor is a drop-in for the old accumulate-everything loop.
* **Copy-on-write datasets** — stages never mutate the flowing dataset
  (``Transformer.transform`` returns a view sharing untouched column
  buffers), so concurrent stages can read the same dataset safely and a
  layer's outputs merge in one ``with_columns`` call.
* **Layer parallelism** — stages within a topological layer are
  independent by construction (layering is by longest path from the raw
  generators, so every input comes from an earlier layer).  Host-side
  stages run concurrently on a bounded thread pool; ``device_heavy``
  stages (models, the selector sweep, SanityChecker) are submitted
  serially in stable layer order so the jit dispatch stream and
  compile-cache accounting stay deterministic.  Results are byte-identical
  to sequential execution because each stage writes exactly one column and
  merge order is the stable layer order (asserted by test).
* **Per-stage profiling** — wall time, rows, columns added/dropped and
  device-launch deltas (``utils/profiling.RunCounters``) per stage, plus
  the peak resident column count, exposed via ``ExecutionPlan.explain()``
  and ``OpWorkflow.train(profile=True)``.
"""
from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..obs import hlo as obs_hlo
from ..obs.trace import begin_span, current_tracer, end_span
from ..stages.base import Estimator, Model, PipelineStage, Transformer
from ..types.columns import ColumnarDataset, FeatureColumn
from ..utils.profiling import (COUNTERS, PlanProfiler, StageProfile,
                               backend_name, current_collector,
                               install_collector)

__all__ = ["ExecutionPlan", "plan_for"]

#: rows below which intra-layer threading is not worth the dispatch overhead
_PARALLEL_ROW_THRESHOLD = int(os.environ.get(
    "TMOG_PLAN_PARALLEL_MIN_ROWS", "4096"))


def _detect_pool_available() -> bool:
    """Intra-layer threading needs >1 usable core (on a single-core host
    pooling GIL-bound stage work is pure context-switch overhead); an
    explicit TMOG_PLAN_WORKERS always wins."""
    if os.environ.get("TMOG_PLAN_WORKERS"):
        return True
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:  # pragma: no cover - non-Linux
        cores = os.cpu_count() or 1
    return cores > 1


_POOL_AVAILABLE = _detect_pool_available()

_POOL: Optional[ThreadPoolExecutor] = None
_POOL_LOCK = threading.Lock()


def _pool() -> ThreadPoolExecutor:
    """Shared bounded pool for host-side stage work (created lazily).

    Stage tasks are leaves (they never submit further pool work), so a
    single process-wide pool cannot deadlock on itself.
    """
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            workers = int(os.environ.get("TMOG_PLAN_WORKERS", "0")) or \
                min(8, max(2, (os.cpu_count() or 4) - 1))
            _POOL = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="tmog-plan")
        return _POOL


def plan_for(dag, keep: Optional[Sequence[str]] = None) -> "ExecutionPlan":
    """The memoized ExecutionPlan for ``dag`` with the given keep-set.

    Cached on the DAG object itself, so every consumer of the same DAG —
    ``train()``, ``transform_dag`` scoring/serving, and each CV fold's
    refit in ``validators.validate_with_dag`` — reuses one plan instead of
    re-deriving liveness per call.
    """
    cache = dag.__dict__.setdefault("_plan_cache", {})
    key = frozenset(keep) if keep is not None else None
    plan = cache.get(key)
    if plan is None:
        plan = cache[key] = ExecutionPlan(dag, keep=keep)
    return plan


class ExecutionPlan:
    """A per-DAG schedule: exec layers, liveness drops, host/device split."""

    def __init__(self, dag, keep: Optional[Sequence[str]] = None):
        self.dag = dag
        self.keep: Optional[frozenset] = (
            frozenset(keep) if keep is not None else None)
        self.layers: List[List[PipelineStage]] = [
            l for l in dag.non_generator_layers() if l]
        self._analyze()

    # -- static analysis -----------------------------------------------------

    def _analyze(self) -> None:
        from ..stages.generator import FeatureGeneratorStage

        # every column name the DAG knows about (raw generator outputs are
        # produced at "layer -1", i.e. present in the input dataset);
        # columns the plan does NOT know (e.g. a reader's "key") are never
        # touched by liveness drops.
        produced_at: Dict[str, int] = {}
        for layer in self.dag.layers:
            for s in layer:
                if isinstance(s, FeatureGeneratorStage):
                    produced_at[s.get_output().name] = -1
        for li, layer in enumerate(self.layers):
            for s in layer:
                produced_at[s.get_output().name] = li
        self.known: Set[str] = set(produced_at)

        # backward closure of stages needed to materialize the keep-set
        # (with keep=None everything is needed)
        out_stage: Dict[str, PipelineStage] = {
            s.get_output().name: s for layer in self.layers for s in layer}
        if self.keep is None:
            self.needed_uids: Set[str] = {
                s.uid for layer in self.layers for s in layer}
        else:
            needed: Set[str] = set()
            frontier = [out_stage[n] for n in self.keep if n in out_stage]
            while frontier:
                s = frontier.pop()
                if s.uid in needed:
                    continue
                needed.add(s.uid)
                for f in s.input_features:
                    p = out_stage.get(f.name)
                    if p is not None:
                        frontier.append(p)
            self.needed_uids = needed

        # last consumer layer per column, in two variants: the fit path
        # executes EVERY stage (all consumers pin their inputs), while the
        # pure-transform path skips non-needed stages.
        def last_use(uids: Optional[Set[str]]) -> Dict[str, int]:
            lu: Dict[str, int] = {}
            for li, layer in enumerate(self.layers):
                for s in layer:
                    if uids is not None and s.uid not in uids:
                        continue
                    for n in s.input_names:
                        lu[n] = li
            return lu

        self._produced_at = produced_at
        self._drops_fit = self._drop_schedule(produced_at, last_use(None))
        self._drops_transform = self._drop_schedule(
            produced_at,
            last_use(self.needed_uids if self.keep is not None else None))

    def _drop_schedule(self, produced_at: Dict[str, int],
                       last_use: Dict[str, int]
                       ) -> Tuple[List[str], List[List[str]]]:
        """(initial_drops, drops_after_layer[i]) for one execution mode.

        A known column not in ``keep`` dies after its last consumer layer;
        a column nobody (executed) consumes dies as soon as it exists —
        raw inputs before layer 0, stage outputs right after their layer.
        No pruning at all when ``keep`` is None.
        """
        n_layers = len(self.layers)
        initial: List[str] = []
        after: List[List[str]] = [[] for _ in range(n_layers)]
        if self.keep is None:
            return initial, after
        for name, pl in produced_at.items():
            if name in self.keep:
                continue
            die = last_use.get(name, pl)
            if die < 0:
                initial.append(name)
            else:
                after[die].append(name)
        initial.sort()
        for l in after:
            l.sort()
        return initial, after

    def required_input_columns(self) -> frozenset:
        """Input-dataset columns the fit path actually reads: every
        executed stage's generator-level (or plan-unknown) inputs plus the
        keep-set.  Callers that copy/slice a dataset before running the
        plan (e.g. per-fold ``take`` in validators) can restrict the copy
        to these instead of gathering every column."""
        req = set(self.keep or ())
        for layer in self.layers:
            for s in layer:
                for n in s.input_names:
                    if self._produced_at.get(n, -1) < 0:
                        req.add(n)
        return frozenset(req)

    # -- reporting -----------------------------------------------------------

    def advise(self, rows: int, cols: int, cost_model=None,
               host_budget_bytes: Optional[int] = None,
               queue_width: Optional[int] = None):
        """Cost-predicted plan-level choices for this DAG at a workload of
        ``rows`` x ``cols``: stream vs in-core, chunk_rows, prefetch
        depth, spill threshold (tuning/planner.py).  ``cost_model`` (a
        tuning.CostModel; default: fitted from the shared history file)
        adds a predicted-wall line and read-vs-transform prefetch
        tuning.  ``queue_width`` (the selector sweep's candidate count)
        additionally attaches a ``mesh`` recommendation — whether and how
        to spread the sweep over a ("data", "grid") device mesh, from the
        cost model's MEASURED ``n_devices`` scaling history when it has
        one (tuning/planner.advise_mesh)."""
        from ..tuning.costmodel import CostModel
        from ..tuning.planner import advise_mesh, advise_plan

        if cost_model is None:
            cost_model = CostModel.from_history()
        advice = advise_plan(rows, cols, cost_model=cost_model,
                             host_budget_bytes=host_budget_bytes,
                             backend=backend_name())
        if queue_width is not None:
            advice.mesh = advise_mesh(rows, cols, queue_width=queue_width,
                                      cost_model=cost_model,
                                      backend=backend_name())
        return advice

    def explain(self, ingest=None, advice=None) -> str:
        """Static plan report: per-layer stages, host/device split, liveness
        drops, and the projected peak resident column count.  Pass an
        ``IngestProfiler`` (``model.ingest_profile`` after a chunked
        ``train(chunk_rows=k)``) to append the out-of-core pass counters —
        per-pass chunks, bytes read, rows/s, overlap efficiency — and/or a
        ``PlanAdvice`` (``plan.advise(rows, cols)``) to append the cost
        planner's stream-vs-in-core recommendation."""
        initial, after = self._drops_fit
        lines = [
            f"ExecutionPlan: {sum(len(l) for l in self.layers)} stages over "
            f"{len(self.layers)} layers"
            + (f", keep={len(self.keep)} columns" if self.keep is not None
               else ", keep=ALL (no pruning)")]
        # simulate resident-column count: raw inputs enter at the start,
        # each layer's outputs append, liveness drops retire
        resident = sum(1 for pl in self._produced_at.values() if pl < 0) \
            - len(initial)
        peak = resident
        if initial:
            lines.append(f"  drop before layer 0: {initial}")
        for li, layer in enumerate(self.layers):
            host = [s for s in layer if not s.device_heavy]
            dev = [s for s in layer if s.device_heavy]
            resident += len(layer)
            peak = max(peak, resident)
            desc = ", ".join(
                f"{type(s).__name__}->{s.get_output().name}" for s in layer)
            par = (f"{len(host)} host-parallel"
                   + (f" + {len(dev)} device-serial" if dev else "")
                   if len(host) > 1 else
                   ("device-serial" if dev and not host else "serial"))
            lines.append(f"  layer {li} [{par}]: {desc}")
            drops = after[li]
            if drops:
                resident -= len(drops)
                lines.append(f"    drop after layer {li}: {drops}")
        lines.append(f"  projected resident columns: peak {peak}, "
                     f"final {resident}")
        if ingest is not None:
            lines.append(ingest.format())
        if advice is not None:
            lines.append(advice.format())
        return "\n".join(lines)

    # -- execution -----------------------------------------------------------

    def fit_and_transform(
        self,
        data: ColumnarDataset,
        apply_to: Optional[ColumnarDataset] = None,
        fitted_substitutes: Optional[Dict[str, Model]] = None,
        profiler: Optional[PlanProfiler] = None,
    ) -> Tuple[List[PipelineStage], ColumnarDataset,
               Optional[ColumnarDataset]]:
        """Fit estimators layer by layer, transforming as we go.

        The ``apply_to`` pass is LAZY/plan-driven: instead of eagerly
        pushing the holdout through every stage as it fits, the fitted
        stages are replayed over ``apply_to`` afterwards through the same
        plan — pruned, skipping stages the keep-set doesn't need.
        """
        subs = fitted_substitutes or {}
        prof = profiler or PlanProfiler()
        t_start = time.perf_counter()
        fitted: List[PipelineStage] = []
        fitted_by_uid: Dict[str, PipelineStage] = {}
        initial, drops_after = self._drops_fit
        if initial:
            data = data.drop(initial)
        prof.note_columns(len(data.columns))

        for li, layer in enumerate(self.layers):
            results = self._run_layer(li, layer, data, subs, prof)
            new_cols: Dict[str, FeatureColumn] = {}
            for stage in layer:
                rs, name, col = results[stage.uid]
                fitted.append(rs)
                fitted_by_uid[stage.uid] = rs
                new_cols[name] = col
            data = data.with_columns(new_cols)
            prof.note_columns(len(data.columns))
            if drops_after[li]:
                data = data.drop(drops_after[li])
                prof.note_drops(li, drops_after[li])
                prof.note_columns(len(data.columns))
        apply_out = None
        if apply_to is not None:
            apply_out = self._transform_with(apply_to, fitted_by_uid, prof)
        prof.wall_s += time.perf_counter() - t_start
        return fitted, data, apply_out

    def transform(self, data: ColumnarDataset,
                  profiler: Optional[PlanProfiler] = None) -> ColumnarDataset:
        """Apply an already-fitted DAG (scoring path), pruned + parallel."""
        for layer in self.layers:
            for stage in layer:
                if isinstance(stage, Estimator):
                    raise RuntimeError(
                        f"unfitted estimator {stage.uid} in scoring DAG")
        prof = profiler or PlanProfiler()
        t_start = time.perf_counter()
        out = self._transform_with(data, None, prof)
        prof.wall_s += time.perf_counter() - t_start
        return out

    def _transform_with(self, data: ColumnarDataset,
                        fitted_by_uid: Optional[Dict[str, PipelineStage]],
                        prof: PlanProfiler) -> ColumnarDataset:
        initial, drops_after = self._drops_transform
        if initial:
            data = data.drop(initial)
        prof.note_columns(len(data.columns))
        for li, layer in enumerate(self.layers):
            run = [s for s in layer if s.uid in self.needed_uids]
            if fitted_by_uid is not None:
                run = [fitted_by_uid[s.uid] for s in run]
            if run:
                results = self._run_layer(li, run, data, _TRANSFORM_ONLY,
                                          prof)
                new_cols = {name: col for _, name, col in
                            (results[s.uid] for s in run)}
                data = data.with_columns(new_cols)
                prof.note_columns(len(data.columns))
            if drops_after[li]:
                data = data.drop(drops_after[li])
                prof.note_columns(len(data.columns))
        return data

    # -- layer executor ------------------------------------------------------

    def _run_layer(self, li: int, layer: List[PipelineStage],
                   data: ColumnarDataset, subs, prof: PlanProfiler
                   ) -> Dict[str, Tuple[PipelineStage, str, FeatureColumn]]:
        """Run one layer's stages: host-side concurrently on the bounded
        pool, device-heavy serially in stable order.  Deterministic: each
        stage computes exactly one column from earlier-layer inputs, and
        the caller merges in stable layer order."""
        n_rows = len(data)
        host = [s for s in layer if not s.device_heavy]
        dev = [s for s in layer if s.device_heavy]
        # TMOG_CHECK instrumented mode freezes/unfreezes the SHARED input
        # buffers around each stage (analysis/contracts.py); concurrent
        # stages would race on the writeable flag, so check mode serializes
        use_pool = (_POOL_AVAILABLE and len(host) > 1
                    and n_rows >= _PARALLEL_ROW_THRESHOLD
                    and os.environ.get("TMOG_CHECK") != "1")
        results: Dict[str, Tuple[PipelineStage, str, FeatureColumn]] = {}

        layer_span = begin_span(f"plan.layer[{li}]", cat="plan",
                                stages=len(layer), rows=n_rows)
        try:
            futures = []
            if use_pool:
                coll = current_collector()
                pool = _pool()
                for stage in host:
                    futures.append((stage, pool.submit(
                        self._run_stage, stage, data, subs, li, n_rows,
                        prof, coll, False, layer_span)))
            else:
                # no pool: run host stages inline, in stable order
                for stage in host:
                    results[stage.uid] = self._run_stage(
                        stage, data, subs, li, n_rows, prof, None, True,
                        layer_span)
            for stage in dev:
                results[stage.uid] = self._run_stage(
                    stage, data, subs, li, n_rows, prof, None, True,
                    layer_span)
            for stage, fut in futures:
                results[stage.uid] = fut.result()
        finally:
            end_span(layer_span)
        return results

    def _run_stage(self, stage: PipelineStage, data: ColumnarDataset,
                   subs, li: int, n_rows: int, prof: PlanProfiler,
                   coll, serial: bool, layer_span=None
                   ) -> Tuple[PipelineStage, str, FeatureColumn]:
        t0 = time.perf_counter()
        launches0 = COUNTERS.launches if serial else 0
        # serial stages own the dispatch stream, so compiled-program
        # features captured during the stage are attributable to it
        # (same discipline as the launch delta); pool stages are host-side
        # and never compile
        hlo_mark = (obs_hlo.mark()
                    if serial and current_tracer() is not None else None)
        stage_span = begin_span(
            f"stage:{type(stage).__name__}", cat="stage",
            parent=layer_span, uid=stage.uid, layer=li,
            output=stage.get_output().name, rows=n_rows,
            device=stage.device_heavy)
        ctx = install_collector(coll) if coll is not None else None
        if ctx is not None:
            ctx.__enter__()
        try:
            if subs is _TRANSFORM_ONLY or not isinstance(stage, Estimator):
                if not isinstance(stage, Transformer):
                    raise TypeError(f"cannot execute stage {stage!r}")
                kind = "transform"
                result_stage = stage
            else:
                sub = subs.get(stage.uid)
                if sub is not None:
                    kind = "substitute"
                    result_stage = sub
                else:
                    kind = "fit"
                    result_stage = stage.fit(data)
            name, col = result_stage.checked_transform_output(data)
        finally:
            if ctx is not None:
                ctx.__exit__(None, None, None)
            end_span(stage_span)
        dt = time.perf_counter() - t0
        stage_hlo = (obs_hlo.aggregate(obs_hlo.since(hlo_mark))
                     if hlo_mark is not None
                     and obs_hlo.mark() > hlo_mark else {})
        width, dtype = _input_shape(stage, data)
        op = type(stage).__name__
        # a stage may refine its cost bucket (e.g. the selector's halving
        # sweeps cost a different law than full sweeps — mixing them would
        # poison both buckets' fits)
        cost_kind = (getattr(stage, "_cost_kind", None)
                     or getattr(result_stage, "_cost_kind", None) or kind)
        from ..utils.profiling import mesh_desc
        n_dev, mshape = mesh_desc(getattr(stage, "mesh", None))
        prof.record_stage(StageProfile(
            uid=stage.uid, op=op, output=name, layer=li,
            kind=kind, device_heavy=stage.device_heavy, wall_s=dt,
            rows=n_rows, cols_added=1,
            launches=(COUNTERS.launches - launches0) if serial else 0,
            cols=width, dtype=dtype, backend=backend_name(),
            stage_kind=f"{op}:{cost_kind}",
            n_devices=n_dev, mesh_shape=mshape, hlo=stage_hlo))
        return result_stage, name, col


def _input_shape(stage: PipelineStage, data: ColumnarDataset):
    """(total scalar width, primary dtype) of a stage's inputs — the cost
    model's feature view of the stage's workload: a vectorizer reading one
    raw column reports width 1, the selector reading a packed (N, D)
    matrix reports D.  Zero-copy: reads only shapes/dtypes."""
    width, dtype = 0, ""
    for n in stage.input_names:
        if n not in data:
            continue
        v = data[n].values
        ndim = getattr(v, "ndim", 1)
        shape = getattr(v, "shape", None)
        width += int(shape[1]) if (ndim >= 2 and shape
                                   and len(shape) > 1) else 1
        if not dtype:
            dtype = str(getattr(v, "dtype", "") or type(v).__name__)
    return max(width, 1), dtype


#: sentinel: _run_layer/_run_stage execute already-fitted transformers only
_TRANSFORM_ONLY = object()
