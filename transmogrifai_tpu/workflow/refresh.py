"""Warm-start refresh — partial_fit a trained workflow from new data.

ROADMAP item 4's missing 10%: the streaming-fit protocol (PR 3) made the
hot fitters mergeable monoids and the streaming driver now exports each
estimator's FINAL state onto the trained model (``model.fit_states``,
persisted with it).  A refresh restores those states and updates them
with new chunks only — ``merge(restored_state, fit_state(new_chunks))``
— so the refreshed model is (within each stage's declared
``streaming_fit_tol``; contract TM027) the model a full streaming
retrain over old+new would produce, at the cost of reading only the new
window.  Non-mergeable tails (e.g. a ModelSelector) refit in-core on the
materialized refresh window.

Feature-geometry guard: a restored downstream state is only valid while
its upstream transforms kept their geometry (same vocab slots, same kept
indices).  ``RefreshContext`` tracks a structural signature per refreshed
model; when new data rotates a vocab or flips a keep decision, every
downstream restored state is invalidated and those estimators refit from
the refresh window alone — counted and reported, never silently wrong.
The guarded swap (serving/guarded.py) remains the deployment backstop
either way.

Checkpointing: a refresh reuses ``StreamingCheckpointManager`` with the
fingerprint extended by the base model's identity, so a SIGKILLed
refresh resumes mid-pass instead of restarting — and a refresh
checkpoint can never resume into a plain train.
"""
from __future__ import annotations

import copy
import hashlib
import json
from typing import Any, Dict, List, Optional, Set

from ..stages.base import Estimator, PipelineStage

__all__ = ["RefreshContext", "RefreshReport", "geometry_signature"]


def align_vocab_order(old: PipelineStage, new: PipelineStage) -> None:
    """Pin slot ORDER across a refresh: when a merged pivot fit produced
    the same category SET as the old model but rotated its order (counts
    shifting between near-tied categories is sampling noise, not
    geometry), reuse the old slot order — downstream sketches accumulated
    per slot stay mergeable.  A genuine set change (a category entering
    or leaving the top-k) is left alone and shows up as a geometry
    change."""
    ov, nv = getattr(old, "vocabs", None), getattr(new, "vocabs", None)
    if ov is None or nv is None or len(ov) != len(nv):
        return
    if getattr(old, "strategies", None) != getattr(new, "strategies", None):
        return
    new.vocabs = [list(o) if set(o) == set(n) else list(n)
                  for o, n in zip(ov, nv)]


def geometry_signature(model: PipelineStage) -> Optional[str]:
    """Structural signature of a fitted model's OUTPUT feature space.

    Two models with equal signatures emit columns whose slots mean the
    same thing, so a downstream sketch accumulated under one remains
    mergeable under the other.  ``None`` = no declared geometry (treated
    as stable; value-only params like fills shift the numbers, not the
    slots).
    """
    sig: Dict[str, Any] = {}
    vocabs = getattr(model, "vocabs", None)
    if vocabs is not None:
        sig["vocabs"] = [[str(v) for v in vocab] for vocab in vocabs]
    strategies = getattr(model, "strategies", None)
    if strategies is not None:
        sig["strategies"] = list(strategies)
    keep = getattr(model, "keep_indices", None)
    if keep is not None:
        sig["keep_indices"] = [int(i) for i in keep]
    fills = getattr(model, "fills", None)
    if fills is not None:
        sig["n_fills"] = len(fills)
    if not sig:
        return None
    return json.dumps(sig, sort_keys=True)


class RefreshReport:
    """What the refresh actually did, per estimator uid."""

    def __init__(self):
        self.merged: List[str] = []          # warm-started from state
        self.refit: List[str] = []           # no state: fit from new data
        self.invalidated: List[str] = []     # upstream geometry changed
        self.geometry_changed: List[str] = []

    def to_json(self) -> Dict[str, Any]:
        return {"merged": sorted(self.merged),
                "refit": sorted(self.refit),
                "invalidated": sorted(self.invalidated),
                "geometryChanged": sorted(self.geometry_changed)}


class RefreshContext:
    """Warm-start state broker for one refresh run.

    The streaming driver asks it for each estimator's initial state
    (``initial_state``) and reports each finished model back
    (``note_finished``) so geometry changes propagate to downstream
    seeding decisions — layers finish strictly before later layers'
    states are created, so the ordering is safe by construction.
    """

    def __init__(self, model, dag):
        from ..utils.profiling import count_refresh

        self._count = count_refresh
        self.states: Dict[str, Any] = dict(getattr(model, "fit_states",
                                                   None) or {})
        self.old_models: Dict[str, PipelineStage] = {
            s.uid: s for s in model.stages}
        self.report = RefreshReport()
        self._changed: Set[str] = set()
        self._ancestors = self._estimator_ancestors(dag)

    @staticmethod
    def _estimator_ancestors(dag) -> Dict[str, Set[str]]:
        """uid -> transitive ESTIMATOR-ancestor uids (via input features'
        origin stages)."""
        memo: Dict[str, Set[str]] = {}

        def walk(stage) -> Set[str]:
            got = memo.get(stage.uid)
            if got is not None:
                return got
            memo[stage.uid] = set()  # cycle guard (DAGs have none)
            anc: Set[str] = set()
            for f in stage.input_features:
                parent = f.origin_stage
                if parent is None:
                    continue
                if isinstance(parent, Estimator):
                    anc.add(parent.uid)
                anc |= walk(parent)
            memo[stage.uid] = anc
            return anc

        for layer in dag.layers:
            for s in layer:
                walk(s)
        return memo

    def base_digest(self) -> Dict[str, Any]:
        """Checkpoint-fingerprint extension identifying the base model —
        a refresh checkpoint only resumes into a refresh of the SAME
        model (state uids + a digest of their geometry)."""
        sigs = {uid: geometry_signature(m) or ""
                for uid, m in sorted(self.old_models.items())}
        digest = hashlib.sha256(
            json.dumps(sigs, sort_keys=True).encode()).hexdigest()[:16]
        return {"refresh": {"stateUids": sorted(self.states),
                            "baseGeometry": digest}}

    # -- driver hooks --------------------------------------------------------

    def initial_state(self, est: Estimator):
        """The restored warm-start state for ``est``, or None when it must
        fit fresh (no exported state, invalidated upstream geometry, or a
        state the estimator can no longer import)."""
        payload = self.states.get(est.uid)
        if payload is None:
            self.report.refit.append(est.uid)
            self._count("refit")
            return None
        if self._ancestors.get(est.uid, set()) & self._changed:
            self.report.invalidated.append(est.uid)
            self._count("invalidated")
            return None
        try:
            # DEEP COPY before import: the default import hook is a
            # passthrough, and update_chunk folds in place — without the
            # copy a refresh would contaminate the base model's stored
            # states (breaking reruns, resume parity, and chained
            # refreshes from the same base)
            state = est.import_fit_state(copy.deepcopy(payload))
        except Exception:
            self.report.invalidated.append(est.uid)
            self._count("invalidated")
            return None
        self.report.merged.append(est.uid)
        self._count("merged")
        return state

    def note_finished(self, est: Estimator, new_model) -> None:
        old = self.old_models.get(est.uid)
        if old is None:
            return
        align_vocab_order(old, new_model)
        if geometry_signature(old) != geometry_signature(new_model):
            self._changed.add(est.uid)
            self.report.geometry_changed.append(est.uid)
            self._count("geometry_changed")
