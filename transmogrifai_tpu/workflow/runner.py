"""Production entry points — run-mode dispatch and app bootstrap.

Reference: ``OpWorkflowRunner`` (core/.../OpWorkflowRunner.scala — run modes
Train/Score/StreamingScore/Features/Evaluate :70,163-296,358-365; config
``OpWorkflowRunnerConfig`` :379; app-end metrics handlers :145), ``OpParams``
(features/.../op/OpParams.scala:81-97), ``OpApp`` bootstrap (OpApp.scala:49-213).

TPU notes: there is no Spark session to bootstrap — ``OpApp`` is a thin
argparse CLI; streaming score pipelines host columnarization against device
scoring through ``AsyncBatcher``.
"""
from __future__ import annotations

import argparse
import enum
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..evaluators.evaluators import OpEvaluatorBase
from ..readers.streaming import AsyncBatcher, StreamingReader
from ..utils.profiling import (AppMetrics, MetricsCollector, OpStep,
                               install_collector, with_job_group)
from .workflow import OpWorkflow, OpWorkflowModel

__all__ = ["RunType", "OpParams", "OpWorkflowRunner",
           "OpWorkflowRunnerResult", "OpApp"]


class RunType(enum.Enum):
    Train = "train"
    Score = "score"
    StreamingScore = "streamingScore"
    Features = "features"
    Evaluate = "evaluate"


@dataclass
class OpParams:
    """JSON/YAML-loadable run configuration (OpParams.scala:81-97 parity)."""

    stage_params: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    reader_params: Dict[str, Any] = field(default_factory=dict)
    model_location: Optional[str] = None
    write_location: Optional[str] = None
    metrics_location: Optional[str] = None
    custom_params: Dict[str, Any] = field(default_factory=dict)
    custom_tag_name: Optional[str] = None
    custom_tag_value: Optional[str] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "OpParams":
        snake = {"stageParams": "stage_params", "readerParams": "reader_params",
                 "modelLocation": "model_location",
                 "writeLocation": "write_location",
                 "metricsLocation": "metrics_location",
                 "customParams": "custom_params",
                 "customTagName": "custom_tag_name",
                 "customTagValue": "custom_tag_value"}
        kwargs = {snake.get(k, k): v for k, v in d.items()}
        return cls(**kwargs)

    @classmethod
    def from_file(cls, path: str) -> "OpParams":
        with open(path) as fh:
            text = fh.read()
        if path.endswith((".yaml", ".yml")):
            try:
                import yaml
                return cls.from_dict(yaml.safe_load(text))
            except ImportError as e:  # pragma: no cover
                raise RuntimeError("pyyaml unavailable; use JSON params") from e
        return cls.from_dict(json.loads(text))

    def to_json(self) -> Dict[str, Any]:
        return {"stageParams": self.stage_params,
                "readerParams": self.reader_params,
                "modelLocation": self.model_location,
                "writeLocation": self.write_location,
                "metricsLocation": self.metrics_location,
                "customParams": self.custom_params}


@dataclass
class OpWorkflowRunnerResult:
    run_type: str
    metrics: Optional[Dict[str, Any]] = None
    summary: Optional[Dict[str, Any]] = None
    scores_location: Optional[str] = None
    n_batches: int = 0
    n_rows: int = 0
    app_metrics: Optional[AppMetrics] = None


class OpWorkflowRunner:
    """Dispatches a workflow through one of the five run modes."""

    def __init__(self,
                 workflow: OpWorkflow,
                 train_reader=None,
                 score_reader=None,
                 streaming_score_reader: Optional[StreamingReader] = None,
                 evaluation_reader=None,
                 evaluator: Optional[OpEvaluatorBase] = None,
                 scoring_evaluator: Optional[OpEvaluatorBase] = None,
                 features_to_compute: Sequence = ()):
        self.workflow = workflow
        self.train_reader = train_reader
        self.score_reader = score_reader
        self.streaming_score_reader = streaming_score_reader
        self.evaluation_reader = evaluation_reader
        self.evaluator = evaluator
        self.scoring_evaluator = scoring_evaluator
        self.features_to_compute = list(features_to_compute)
        self._end_handlers: List[Callable[[AppMetrics], None]] = []

    def add_application_end_handler(
            self, fn: Callable[[AppMetrics], None]) -> None:
        """Called with the run's AppMetrics when run() completes
        (OpWorkflowRunner.scala:145)."""
        self._end_handlers.append(fn)

    # -- dispatch ------------------------------------------------------------

    def run(self, run_type: RunType, params: Optional[OpParams] = None
            ) -> OpWorkflowRunnerResult:
        params = params or OpParams()
        collector = MetricsCollector(run_type=run_type.value)
        for fn in self._end_handlers:
            collector.add_application_end_handler(fn)
        if params.custom_tag_name:
            collector.metrics.custom_tags[params.custom_tag_name] = (
                params.custom_tag_value or "")
        if params.stage_params:
            self.workflow.set_parameters(params.stage_params)
        dispatch = {RunType.Train: self._train,
                    RunType.Score: self._score,
                    RunType.StreamingScore: self._streaming_score,
                    RunType.Features: self._features,
                    RunType.Evaluate: self._evaluate}
        with install_collector(collector):
            result = dispatch[run_type](params)
        result.app_metrics = collector.finish()
        self._write_metrics(params, result)
        return result

    # -- modes ---------------------------------------------------------------

    def _train(self, params: OpParams) -> OpWorkflowRunnerResult:
        if self.train_reader is not None:
            self.workflow.set_reader(self.train_reader)
        # custom_params.profile=true turns on the execution plan's per-stage
        # profile; it rides along in the train summary (and thence the
        # metrics_location JSON) as "executionProfile".
        # custom_params.chunk_rows=k selects the out-of-core chunked train
        # (workflow/streaming.py); its pass counters ride along as
        # "ingestProfile".
        profile = bool(params.custom_params.get("profile"))
        chunk_rows = params.custom_params.get("chunk_rows")
        model = self.workflow.train(
            profile=profile,
            chunk_rows=int(chunk_rows) if chunk_rows else None)
        if params.model_location:
            with with_job_group(OpStep.ModelIO):
                model.save(params.model_location)
        summary = model.summary()
        if profile and model.train_profile is not None:
            summary["executionProfile"] = model.train_profile.to_json()
        if model.ingest_profile is not None:
            summary["ingestProfile"] = model.ingest_profile.to_json()
        return OpWorkflowRunnerResult(run_type="train", summary=summary)

    def _load_model(self, params: OpParams) -> OpWorkflowModel:
        if not params.model_location:
            raise ValueError("model_location required")
        with with_job_group(OpStep.ModelIO):
            return OpWorkflowModel.load(params.model_location)

    def _write_scores(self, scored, params: OpParams,
                      suffix: str = "") -> Optional[str]:
        if not params.write_location:
            return None
        with with_job_group(OpStep.ResultsSaving):
            os.makedirs(params.write_location, exist_ok=True)
            path = os.path.join(params.write_location, f"scores{suffix}.csv")
            scored.to_pandas().to_csv(path, index=False)
        return path

    def _score(self, params: OpParams) -> OpWorkflowRunnerResult:
        model = self._load_model(params)
        if self.score_reader is not None:
            model.set_reader(self.score_reader)
        with with_job_group(OpStep.Scoring):
            scored = model.score()
            metrics = None
            if self.scoring_evaluator is not None:
                metrics = model.evaluate(self.scoring_evaluator, scored=scored)
        path = self._write_scores(scored, params)
        return OpWorkflowRunnerResult(run_type="score", metrics=metrics,
                                      scores_location=path,
                                      n_rows=len(scored))

    def _streaming_score(self, params: OpParams) -> OpWorkflowRunnerResult:
        if self.streaming_score_reader is None:
            raise ValueError("streamingScore requires a streaming score reader")
        model = self._load_model(params)
        raw = model.raw_features()
        # prefetch thread columnarizes batch k+1 while the device scores k
        batches = AsyncBatcher(
            self.streaming_score_reader.stream(raw))
        n_batches = n_rows = 0
        path = None
        try:
            for batch in batches:
                with with_job_group(OpStep.Scoring):
                    scored = model.score(data=batch)
                p = self._write_scores(scored, params,
                                       suffix=f"_{n_batches:05d}")
                path = path or (params.write_location if p else None)
                n_batches += 1
                n_rows += len(scored)
        finally:
            batches.close()  # releases the pump thread on scoring errors
        return OpWorkflowRunnerResult(run_type="streamingScore",
                                      scores_location=path,
                                      n_batches=n_batches, n_rows=n_rows)

    def _features(self, params: OpParams) -> OpWorkflowRunnerResult:
        if self.train_reader is not None:
            self.workflow.set_reader(self.train_reader)
        if self.features_to_compute:
            data = self.workflow.compute_data_up_to(
                self.features_to_compute[-1])
        else:
            with with_job_group(OpStep.DataReadingAndFiltering):
                data = self.workflow.generate_raw_data()
        path = self._write_scores(data, params)
        return OpWorkflowRunnerResult(run_type="features",
                                      scores_location=path, n_rows=len(data))

    def _evaluate(self, params: OpParams) -> OpWorkflowRunnerResult:
        if self.evaluator is None:
            raise ValueError("evaluate requires an evaluator")
        model = self._load_model(params)
        if self.evaluation_reader is not None:
            model.set_reader(self.evaluation_reader)
        with with_job_group(OpStep.Scoring):
            scored, metrics = model.score_and_evaluate(self.evaluator)
        path = self._write_scores(scored, params)
        return OpWorkflowRunnerResult(run_type="evaluate", metrics=metrics,
                                      scores_location=path,
                                      n_rows=len(scored))

    def _write_metrics(self, params: OpParams,
                       result: OpWorkflowRunnerResult) -> None:
        if not params.metrics_location:
            return
        os.makedirs(params.metrics_location, exist_ok=True)
        out = {"runType": result.run_type, "metrics": result.metrics,
               "app": result.app_metrics.to_json()
               if result.app_metrics else None}
        path = os.path.join(params.metrics_location, "op_metrics.json")
        from ..utils.jsonio import write_json_atomic

        write_json_atomic(path, out, indent=2)  # tmp + os.replace (TM050)


class OpApp:
    """Abstract application bootstrap (OpApp.scala:49-213 parity): subclass,
    implement ``runner()``, then ``MyApp().main(argv)``."""

    app_name = "transmogrifai_tpu_app"

    def runner(self) -> OpWorkflowRunner:
        raise NotImplementedError

    def parser(self) -> argparse.ArgumentParser:
        p = argparse.ArgumentParser(self.app_name)
        p.add_argument("--run-type", required=True,
                       choices=[r.value for r in RunType])
        p.add_argument("--param-location", default=None,
                       help="JSON/YAML OpParams file")
        p.add_argument("--model-location", default=None)
        p.add_argument("--write-location", default=None)
        p.add_argument("--metrics-location", default=None)
        return p

    def main(self, argv: Optional[Sequence[str]] = None
             ) -> OpWorkflowRunnerResult:
        args = self.parser().parse_args(argv)
        params = (OpParams.from_file(args.param_location)
                  if args.param_location else OpParams())
        for name in ("model_location", "write_location", "metrics_location"):
            v = getattr(args, name)
            if v:
                setattr(params, name, v)
        run_type = next(r for r in RunType if r.value == args.run_type)
        return self.runner().run(run_type, params)
