"""Out-of-core training — the chunked two-pass fit driver.

Reference: the reference leans on Spark so training data never has to fit
in one executor's heap; the TPU port's readers instead materialized whole
files, making host RAM the binding constraint on dataset size.  This
module decouples them, following the external-memory two-pass design of
"XGBoost: Scalable GPU Accelerated Learning" (arXiv:1806.11248): sketch
passes build mergeable fit states chunk by chunk, then the final work
writes only the columns the rest of the pipeline actually needs.

Shape of a run (``OpWorkflow.train(chunk_rows=k)``):

1. **Streamable prefix** — the longest prefix of DAG layers in which every
   estimator implements the streaming-fit protocol
   (``stages/base.Estimator``: begin_fit / update_chunk / merge_states /
   finish_fit).  Estimator layers fit in sequence; each bounded chunk
   flows through the already-fitted upstream stages with per-chunk
   liveness pruning.  No full-dataset intermediate column ever exists.
2. **Fused retention point** — the reader is re-read only while upstream
   models are still unfitted (at most two reader passes).  The second
   estimator-layer pass doubles as the RETENTION pass: while its fit
   states accumulate, the pass direct-writes every needed column already
   computable into preallocated full-length buffers and retains, per
   chunk, exactly the columns the remaining pipeline needs (for the
   canonical pipeline: the combined pre-SanityChecker matrix) as blocks.
3. **Block cascade** — every LATER estimator layer and the final assembly
   run over the retained blocks, never the reader: each block transforms
   through the stages fitted so far (e.g. the SanityChecker model's
   index gather), feeds the next layer's fit states, and is re-retained
   as views over the preallocated packed (N, D) float32 output buffers —
   each input block is freed as it is consumed, so the input and output
   matrices never coexist in full.  This extends the execution plan's
   liveness story (workflow/plan.py, "drop after last consumer") to
   "never materialize" for every other intermediate, and transforms each
   row through the expensive featurizers exactly ONCE.
4. **Tail** — remaining layers (a non-streamable estimator, e.g. the
   model selector or SanityChecker with Spearman) run in-core on the
   materialized dataset through the ordinary execution plan — the
   paper's split: sketchable statistics stream; the trainer consumes the
   packed matrix.

Chunk parsing overlaps compute: the reader side of each pass runs on the
``AsyncBatcher`` prefetch thread (readers/streaming.py), parsing chunk
k+1 while chunk k runs through the transform layers; per-chunk wall,
bytes read, rows/s and overlap-efficiency counters land in
``utils/profiling.IngestProfiler`` (surfaced via ``train(profile=True)``
and ``ExecutionPlan.explain``).

Memory note: block retention totals one pass worth of the downstream
chain's INPUT columns.  When the retention point's chain is fed directly
by raw object columns (a DAG with a single estimator layer) the
retention approaches the raw dataset's size — no worse than in-core, and
still one reader pass cheaper.

Fault tolerance (docs/robustness.md): when the reader carries a
``ResilienceConfig`` (``reader.with_resilience(...)``), every pass's chunk
stream is wrapped in the retry/backoff ``RetryingChunkStream`` and
bad-record quarantine counts land in the ingest profiler; with
``checkpoint_dir`` set, pure fit passes checkpoint their mergeable states
every ``checkpoint_every`` chunks and completed passes persist their
fitted models, so a killed process resumes instead of refitting
(workflow/checkpoint.py).
"""
from __future__ import annotations

import os
import tempfile
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..readers.streaming import AsyncBatcher
from ..stages.base import Estimator, Model, PipelineStage, Transformer
from ..types.columns import ColumnarDataset, FeatureColumn
from ..utils.profiling import (IngestPass, IngestProfiler, PlanProfiler,
                               StageProfile, current_collector)

__all__ = ["fit_dag_streaming"]

#: retained-block budget (MB) before the fused pass spills blocks to a
#: temp file — the classic external-memory move: sequential write during
#: the retention pass, sequential read-back during the cascade, so peak
#: host memory stays bounded by the packed OUTPUT, not the retained input
_RETAIN_MB_ENV = "TMOG_STREAM_RETAIN_MB"
_RETAIN_MB_DEFAULT = 256


def _retain_budget_bytes(retain_mb: Optional[float] = None) -> int:
    """Block-retention budget: an explicit ``retain_mb`` (the cost
    planner's spill-threshold advice, tuning/planner.py) wins over the
    env knob wins over the default."""
    if retain_mb is not None:
        return int(float(retain_mb) * (1 << 20))
    try:
        mb = float(os.environ.get(_RETAIN_MB_ENV, "") or _RETAIN_MB_DEFAULT)
    except ValueError:
        mb = _RETAIN_MB_DEFAULT
    return int(mb * (1 << 20))


class _BlockStore:
    """Retained per-chunk blocks with disk spill past a byte budget.

    Blocks under the budget stay in RAM; once the running total crosses
    it, every FURTHER block's arrays are appended to one temp file
    (``np.save`` per column, sequential) and reloaded on ``pop`` — blocks
    are consumed once, in order, so the read-back is sequential too.
    """

    def __init__(self, budget_bytes: int):
        self._budget = budget_bytes
        self._bytes = 0
        self._mem: List[Optional[ColumnarDataset]] = []
        self._meta: List[Optional[List[tuple]]] = []  # spilled block layout
        self._fh = None
        self._path: Optional[str] = None
        self.spilled_blocks = 0
        self.spilled_bytes = 0

    def __len__(self) -> int:
        return len(self._mem)

    def _ds_bytes(self, ds: ColumnarDataset) -> int:
        return sum(np.asarray(c.values).nbytes for c in ds.columns.values())

    def append(self, ds: ColumnarDataset) -> None:
        nbytes = self._ds_bytes(ds)
        if self._bytes + nbytes <= self._budget and self._fh is None:
            self._bytes += nbytes
            self._mem.append(ds)
            self._meta.append(None)
            return
        if self._fh is None:
            fd, self._path = tempfile.mkstemp(prefix="tmog_spill_",
                                              suffix=".npy")
            self._fh = os.fdopen(fd, "w+b")
        layout = []
        for name, col in ds.columns.items():
            offset = self._fh.tell()
            np.save(self._fh, np.asarray(col.values), allow_pickle=True)
            mask_off = None
            if col.mask is not None:
                mask_off = self._fh.tell()
                np.save(self._fh, np.asarray(col.mask))
            layout.append((name, col.ftype, col.vmeta, offset, mask_off))
        self._mem.append(None)
        self._meta.append(layout)
        self.spilled_blocks += 1
        self.spilled_bytes += nbytes

    def pop(self, i: int) -> ColumnarDataset:
        ds = self._mem[i]
        if ds is not None:
            self._mem[i] = None
            return ds
        layout = self._meta[i]
        self._meta[i] = None
        cols: Dict[str, FeatureColumn] = {}
        for name, ftype, vmeta, offset, mask_off in layout:
            self._fh.seek(offset)
            values = np.load(self._fh, allow_pickle=True)
            mask = None
            if mask_off is not None:
                self._fh.seek(mask_off)
                mask = np.load(self._fh)
            cols[name] = FeatureColumn(ftype, values, mask, vmeta)
        return ColumnarDataset(cols, _validated=True)

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            finally:
                self._fh = None
            if self._path is not None:
                try:
                    os.unlink(self._path)
                except OSError:  # pragma: no cover
                    pass
                self._path = None


class _TimedChunks:
    """Wraps a reader ChunkStream with read-side timing; runs on the
    prefetch pump thread, so producer time is attributed even while the
    consumer is busy transforming the previous chunk."""

    def __init__(self, stream, pass_stats: IngestPass):
        self._stream = iter(stream)
        self._pass = pass_stats
        self._last_bytes = 0

    def __iter__(self):
        return self

    def __next__(self):
        t0 = time.perf_counter()
        ds = next(self._stream)
        dt = time.perf_counter() - t0
        nb = int(getattr(self._stream, "bytes_read", 0) or 0)
        delta, self._last_bytes = nb - self._last_bytes, nb
        self._pass.note_read(len(ds), dt, max(delta, 0))
        return ds


class _ColumnWriter:
    """Writes per-chunk columns into preallocated full-length buffers.

    With ``total`` known (any earlier pass counted the rows) buffers
    preallocate once — the packed (N, D) float32 feature matrix path;
    with unknown N, chunk arrays accumulate and concatenate at finish.
    ``row_view`` hands back zero-copy row-range views of a written
    buffer — the block cascade re-retains written columns as views so
    the bytes are never held twice.

    ``shard_onto``/``shard_columns`` is the streaming→sharded hand-off
    (ROADMAP item 1): a designated 2-D float column's rows stream
    straight into per-shard DEVICE buffers (``parallel.ingest.
    ShardedMatrixWriter`` — each completed data-shard slice ``device_put``
    and the host slice buffer reused), so the packed (N, D) matrix never
    materializes on the host.  Sharding engages only when the contiguity
    and shape preconditions hold (known total, maskless 2-D float column,
    writes starting at row 0); otherwise that column silently takes the
    host path — correctness never depends on the fast path.
    """

    def __init__(self, total_rows: Optional[int], shard_onto=None,
                 shard_columns: Optional[Set[str]] = None):
        self.total = total_rows
        self.cols: Dict[str, dict] = {}
        self.offset = 0
        self._mesh = shard_onto
        self._shard_cols = set(shard_columns or ())

    def _maybe_shard_writer(self, name: str, col: FeatureColumn):
        if (self._mesh is None or name not in self._shard_cols
                or self.total is None or self.offset != 0
                or col.mask is not None):
            return None
        v = np.asarray(col.values)
        if v.ndim != 2 or not np.issubdtype(v.dtype, np.floating):
            return None
        from ..parallel.ingest import ShardedMatrixWriter

        return ShardedMatrixWriter(self._mesh, self.total,
                                   int(v.shape[1]), dtype=np.float32)

    def append(self, chunk: ColumnarDataset, names: Sequence[str]) -> None:
        n = len(chunk)
        for name in names:
            col = chunk[name]
            ent = self.cols.get(name)
            if ent is None:
                ent = self.cols[name] = {
                    "ftype": col.ftype, "vmeta": col.vmeta,
                    "has_mask": col.mask is not None,
                    "values": None, "mask": None, "parts": [],
                    "mask_parts": [], "swriter":
                        self._maybe_shard_writer(name, col)}
                if self.total is not None and ent["swriter"] is None:
                    v = np.asarray(col.values)
                    ent["values"] = np.empty((self.total,) + v.shape[1:],
                                             dtype=v.dtype)
                    if ent["has_mask"]:
                        ent["mask"] = np.empty(self.total, dtype=bool)
            sw = ent.get("swriter")
            if sw is not None:
                if sw.offset != self.offset:  # pragma: no cover - guarded
                    raise RuntimeError(
                        f"sharded column {name!r} written out of order "
                        f"(writer at {sw.offset}, pass at {self.offset})")
                sw.append(np.asarray(col.values, np.float32))
            elif ent["values"] is not None:
                ent["values"][self.offset:self.offset + n] = col.values
                if ent["has_mask"]:
                    ent["mask"][self.offset:self.offset + n] = col.mask
            else:
                ent["parts"].append(np.asarray(col.values))
                if ent["has_mask"]:
                    ent["mask_parts"].append(np.asarray(col.mask))
        self.offset += n

    def close(self) -> None:
        """Abort-path cleanup: release every unfinished sharded column
        writer's device buffers + reusable host slice
        (``ShardedMatrixWriter.close``).  A mid-shard ingest failure
        would otherwise strand the committed shards on device for the
        writer's lifetime.  Finished writers already released; idempotent
        (the driver calls this in ``finally`` — the _BlockStore
        pattern)."""
        for ent in self.cols.values():
            sw = ent.get("swriter")
            if sw is not None and not getattr(sw, "_closed", True):
                sw.close()

    def row_view(self, name: str, start: int,
                 stop: int) -> Optional[FeatureColumn]:
        ent = self.cols.get(name)
        if ent is None or ent["values"] is None:
            return None
        mask = ent["mask"][start:stop] if ent["has_mask"] else None
        return FeatureColumn(ent["ftype"], ent["values"][start:stop],
                             mask, ent["vmeta"])

    def finish(self) -> Dict[str, FeatureColumn]:
        from ..parallel.ingest import ShardedMatrix

        out: Dict[str, FeatureColumn] = {}
        for name, ent in self.cols.items():
            sw = ent.get("swriter")
            if sw is not None:
                values = ShardedMatrix(sw.finish(), self.total)
                out[name] = FeatureColumn(ent["ftype"], values, None,
                                          ent["vmeta"])
                continue
            values = (ent["values"] if ent["values"] is not None
                      else np.concatenate(ent["parts"]))
            mask = None
            if ent["has_mask"]:
                mask = (ent["mask"] if ent["mask"] is not None
                        else np.concatenate(ent["mask_parts"]))
            out[name] = FeatureColumn(ent["ftype"], values, mask,
                                      ent["vmeta"])
        return out


def _est_name(est) -> str:
    """Display name of an estimator (unwraps the fold-tagged CV proxy)."""
    return type(getattr(est, "inner", est)).__name__


def _split_streamable(layers: List[List[PipelineStage]],
                      subs: Dict[str, Model]) -> int:
    """Index of the first layer containing an estimator that cannot stream
    (and is not warm-start substituted) — everything from there on runs
    in-core on the materialized dataset."""
    for i, layer in enumerate(layers):
        for s in layer:
            if (isinstance(s, Estimator) and s.uid not in subs
                    and not s.supports_streaming_fit):
                return i
    return len(layers)


def _closure(targets: Sequence[str],
             out_stage: Dict[str, PipelineStage]) -> Set[str]:
    """Uids of stages needed (transitively) to produce ``targets``."""
    needed: Set[str] = set()
    frontier = [out_stage[n] for n in targets if n in out_stage]
    while frontier:
        s = frontier.pop()
        if s.uid in needed:
            continue
        needed.add(s.uid)
        for f in s.input_features:
            p = out_stage.get(f.name)
            if p is not None:
                frontier.append(p)
    return needed


def _liveness(ordered: List[PipelineStage],
              final_needed: Set[str]) -> List[Set[str]]:
    """needed_after[i]: columns that must survive past ordered[i] — inputs
    of the remaining stages plus the pass's final targets."""
    needed_after: List[Set[str]] = [set(final_needed) for _ in ordered]
    running = set(final_needed)
    for i in range(len(ordered) - 1, -1, -1):
        needed_after[i] = set(running)
        running |= set(ordered[i].input_names)
    return needed_after


def fit_dag_streaming(
    dag,
    reader,
    raw_features,
    chunk_rows: int,
    keep: Optional[Sequence[str]] = None,
    fitted_substitutes: Optional[Dict[str, Model]] = None,
    profiler: Optional[PlanProfiler] = None,
    prefetch: int = 2,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 16,
    retain_mb: Optional[float] = None,
    shard_onto=None,
    shard_columns: Optional[Sequence[str]] = None,
    refresh_ctx=None,
    fingerprint_extra: Optional[Dict] = None,
    cv_ctx=None,
    chunk_filter=None,
    pod_ctx=None,
) -> Tuple[List[PipelineStage], ColumnarDataset, IngestProfiler,
           Dict[str, object]]:
    """Fit ``dag`` from chunked ingestion; returns (fitted stages in topo
    order, final dataset equivalent to the in-core executor's with the
    same ``keep``, ingest counters, exported final fit states by uid).

    The returned FIT STATES are each streamed estimator's final mergeable
    state through its ``export_fit_state`` hook — the warm-start capital
    a later ``OpWorkflow.refresh`` resumes from (they ride on the model
    as ``fit_states`` and persist with it).

    ``refresh_ctx`` (a ``workflow.refresh.RefreshContext``) turns this
    run into a WARM-START refresh: estimators whose restored state is
    still valid begin from it (so chunks here are a partial_fit on top of
    the original training data), and geometry changes invalidate
    downstream restored states (those estimators refit from this reader
    alone).  ``fingerprint_extra`` extends the checkpoint fingerprint so
    a refresh checkpoint can never resume into a plain train (or a
    refresh of a different base model).

    ``checkpoint_dir`` enables chunk-level checkpoint/resume: pure fit
    passes persist their mergeable states every ``checkpoint_every``
    chunks, completed passes persist their fitted models, and a rerun
    against the same directory resumes from the last durable point
    (workflow/checkpoint.py has the recovery matrix).

    ``shard_onto`` (a device mesh) + ``shard_columns`` stream the named
    packed float matrices straight into per-shard device buffers instead
    of one host buffer (the streaming→sharded hand-off; see
    ``_ColumnWriter`` and ``parallel.ingest``) — the mesh sweep then
    consumes the committed row-sharded array without a host round trip.

    ``cv_ctx`` (a ``workflow.streaming_cv.StreamingCVContext``) turns
    this run into a streaming WORKFLOW-CV train: during-DAG estimators
    accumulate fold-tagged states (one mergeable state per fold, fold
    ids assigned per global row id), and after the prefix materializes
    the context runs the fold validation (per-fold models from merged
    complement states) so the tail's ModelSelector consumes the winner.
    With a checkpoint manager attached the fold-tagged layers run as
    dedicated checkpointable passes (fold states are part of the
    mid-pass cursor — a mid-fold kill resumes bit-exactly) at the cost
    of one extra reader pass.

    ``chunk_filter`` (dataset -> dataset) runs on every RAW chunk of
    every pass before any transform — RawFeatureFilter's map-key
    cleaning rides here, so chunking never changes what the DAG sees.

    ``pod_ctx`` (a ``distributed.podstream.PodStreamContext``) turns
    this run into ONE MEMBER of a multi-process pod train: this process
    streams only its host ranges, per-pass states allgather-merge in
    host order (every process finishes each pass with identical merged
    states), the materialized columns gather after an RSS probe, and
    every durable artifact is written by the coordinator behind a
    barrier.  Checkpoints store one record per ORIGINAL host, so a
    SIGKILLed pod train resumes bit-exactly under ANY process count
    (``pod.processCount`` is advisory in the fingerprint)."""
    from .dag import StagesDAG, fit_and_transform_dag

    if chunk_rows <= 0:
        raise ValueError(f"chunk_rows must be positive, got {chunk_rows}")
    if pod_ctx is not None and (refresh_ctx is not None
                                or shard_onto is not None):
        raise ValueError(
            "pod trains do not yet compose with warm-start refresh or "
            "the shard_onto device hand-off — run those single-process")
    subs = dict(fitted_substitutes or {})
    layers = [l for l in dag.non_generator_layers() if l]
    split = _split_streamable(layers, subs)
    prefix, tail = layers[:split], layers[split:]
    ingest = IngestProfiler(chunk_rows)
    if profiler is not None:
        profiler.ingest = ingest

    manager = None
    resume = None
    if checkpoint_dir is not None:
        from .checkpoint import (CheckpointMismatchError,
                                 StreamingCheckpointManager,
                                 compute_fingerprint)

        fingerprint = compute_fingerprint(reader, raw_features, layers,
                                          chunk_rows)
        if fingerprint_extra:
            fingerprint = {**fingerprint, **fingerprint_extra}
        if pod_ctx is not None:
            # advisory: recorded for the diff message, never compared
            fingerprint = {**fingerprint,
                           "advisory": pod_ctx.fingerprint_advisory()}
        manager = StreamingCheckpointManager(
            checkpoint_dir, fingerprint,
            every_chunks=checkpoint_every)
        resume = manager.load()
        if resume is not None:
            ingest.resumed = True
            if pod_ctx is None and resume.pod is not None:
                raise CheckpointMismatchError(
                    f"checkpoint in {checkpoint_dir!r} was written by a "
                    f"{resume.pod.get('processCount')}-process pod train; "
                    f"resume it under the pod runtime (a pod of 1 works: "
                    f"`tmog pod -n 1 ...`)")
            if pod_ctx is not None:
                pod_ctx.adopt_resume(resume)
    if pod_ctx is not None:
        if manager is not None:
            manager.pod_record = pod_ctx.pod_record()
        reader = pod_ctx.local_reader()

    rcfg = getattr(reader, "resilience", None)
    sink = rcfg.sink() if (rcfg is not None and rcfg.quarantines) else None
    q0_records = sink.count if sink is not None else 0
    q0_rows = sink.rows if sink is not None else 0

    def _note_checkpoint(t0: float) -> None:
        ingest.checkpoint_saves = manager.saves
        ingest.checkpoint_wall_s += time.perf_counter() - t0

    raw_names = {f.name for f in raw_features}
    out_stage: Dict[str, PipelineStage] = {
        s.get_output().name: s for layer in prefix for s in layer}
    known_universe = raw_names | {
        s.get_output().name for layer in layers for s in layer}
    fitted_by_uid: Dict[str, PipelineStage] = {}
    stage_wall: Dict[str, float] = {}
    stage_layer: Dict[str, int] = {
        s.uid: li for li, layer in enumerate(prefix) for s in layer}
    stage_kind: Dict[str, str] = {}
    #: GLOBAL row count (pod: known up front from the shard plan; single
    #: process: learned from the first completed pass)
    total_rows: Optional[int] = (None if pod_ctx is None
                                 else pod_ctx.total_rows)
    coll = current_collector()
    extras: Set[str] = set()  # plan-unknown passthroughs (e.g. "key")

    def fitted_of(stage: PipelineStage) -> PipelineStage:
        if isinstance(stage, Estimator):
            got = fitted_by_uid.get(stage.uid) or subs.get(stage.uid)
            if got is None:  # pragma: no cover - pass ordering guarantees it
                raise RuntimeError(f"stage {stage.uid} used before fit")
            return got
        return stage

    def timed_transform(stage: PipelineStage,
                        ds: ColumnarDataset) -> ColumnarDataset:
        f = fitted_of(stage)
        t0 = time.perf_counter()
        out = f.transform(ds)
        stage_wall[stage.uid] = (stage_wall.get(stage.uid, 0.0)
                                 + time.perf_counter() - t0)
        return out

    def run_reader_pass(label: str, ordered: List[PipelineStage],
                        final_needed: Set[str], per_chunk,
                        keep_unknown: bool, skip_chunks: int = 0,
                        on_chunk=None, pod_skips=None,
                        on_pod_entry=None, on_pod_chunk=None) -> int:
        """One prefetch-overlapped pass over the reader's chunks: transform
        through ``ordered`` (liveness-pruned), then hand the chunk to
        ``per_chunk``.  Returns the row count (LOCAL rows under a pod).

        With a reader-side retry policy the chunk stream is wrapped in the
        resilience layer's ``RetryingChunkStream`` (transient IO errors
        back off and re-read; the wrapper re-skips delivered chunks
        exactly).  ``skip_chunks`` fast-skips a checkpoint resume's
        already-consumed chunks — read, counted, but neither transformed
        nor handed to ``per_chunk``.  ``on_chunk(idx, rows_so_far)`` runs
        after each consumed chunk (the checkpoint cadence hook).

        Under a pod the pass iterates this process's HOST ENTRIES in
        order, one windowed stream per entry (each entry's chunk grid is
        deterministic, so the pod checkpoint's per-entry cursors are
        exact): ``pod_skips`` gives the per-entry resume skip,
        ``on_pod_entry(entry_pos)`` fires before an entry's first chunk
        (state-routing hook) and ``on_pod_chunk(entry_pos, chunks_done)``
        after every consumed chunk (the pod checkpoint cadence)."""
        from ..obs.trace import begin_span, end_span

        pass_stats = ingest.begin_pass(label)
        if cv_ctx is not None:
            cv_ctx.begin_label_pass()
        needed_after = _liveness(ordered, final_needed)

        if pod_ctx is not None:
            sources = []
            for pos, entry in enumerate(pod_ctx.entries):
                skip = pod_skips[pos] if pod_skips else 0
                sources.append((pos, entry.range,
                                (lambda _r=entry.range:
                                 pod_ctx.inner_reader.iter_chunks(
                                     raw_features, chunk_rows,
                                     host_range=_r)),
                                skip))
        else:
            sources = [(None, (0, 0),
                        lambda: reader.iter_chunks(raw_features,
                                                   chunk_rows),
                        skip_chunks)]

        rows = 0
        total_chunks = 0
        pass_span = begin_span(f"ingest.pass:{label}", cat="ingest",
                               stages=len(ordered),
                               skip_chunks=skip_chunks)
        t_pass = time.perf_counter()
        try:
            for src_pos, src_range, factory, src_skip in sources:
                if rcfg is not None and rcfg.retry is not None:
                    from ..readers.resilience import RetryingChunkStream

                    stream = RetryingChunkStream(
                        factory, rcfg.retry,
                        on_retry=pass_stats.note_retry)
                else:
                    stream = factory()
                source = _TimedChunks(stream, pass_stats)
                batcher = AsyncBatcher(source, depth=prefetch)
                if on_pod_entry is not None:
                    on_pod_entry(src_pos)
                local_idx = 0
                local_row = 0
                try:
                    for chunk in batcher:
                        if chunk_filter is not None:
                            chunk = chunk_filter(chunk)
                        if cv_ctx is not None and cv_ctx.collecting_labels:
                            # fold assignment needs (n, y) up front: the
                            # label column is collected from the RAW
                            # chunks of the first executed pass (skipped
                            # chunks are still read, so a mid-pass resume
                            # collects them too)
                            cv_ctx.collect_labels(chunk)
                        # the saver callback rendezvouses per CHUNK
                        # INDEX, which both the skip and the process
                        # path advance identically on every host
                        if local_idx < src_skip:  # tmog: disable=TM071
                            rows += len(chunk)
                            local_row += len(chunk)
                            pass_stats.chunks_skipped += 1
                            local_idx += 1
                            total_chunks += 1
                            if on_pod_chunk is not None:
                                on_pod_chunk(src_pos, local_idx)
                            continue
                        t0 = time.perf_counter()
                        chunk_span = begin_span(
                            f"ingest.chunk[{total_chunks}]",
                            cat="ingest", parent=pass_span,
                            rows=len(chunk))
                        ds = chunk
                        try:
                            if total_chunks == 0 and keep_unknown:
                                extras.update(c for c in ds.names()
                                              if c not in known_universe)
                            for idx, st in enumerate(ordered):
                                ds = timed_transform(st, ds)
                                na = needed_after[idx]
                                ds = ds.select(
                                    [c for c in ds.names()
                                     if c in na or (keep_unknown and
                                                    c not in
                                                    known_universe)])
                            if cv_ctx is not None:
                                # GLOBAL row window of this chunk —
                                # fold-tagged update_chunks slice their
                                # fold ids from it (pod: offset by the
                                # entry's global range start)
                                base = (rows if pod_ctx is None
                                        else src_range[0] + local_row)
                                cv_ctx.set_window(base, len(chunk))
                            per_chunk(ds, local_idx)
                        finally:
                            end_span(chunk_span)
                        rows += len(chunk)
                        local_row += len(chunk)
                        pass_stats.note_transform(total_chunks,
                                                  time.perf_counter() - t0)
                        local_idx += 1
                        total_chunks += 1
                        if on_chunk is not None:
                            on_chunk(local_idx - 1, rows)
                        if on_pod_chunk is not None:
                            on_pod_chunk(src_pos, local_idx)
                finally:
                    batcher.close()
        finally:
            end_span(pass_span, chunks=total_chunks, rows=rows)
        pass_stats.wall_s = time.perf_counter() - t_pass
        if rows == 0:
            raise ValueError("chunked reader produced no rows")
        if cv_ctx is not None:
            cv_ctx.finish_label_pass(rows)
            if pod_ctx is not None and cv_ctx.labels_ready \
                    and not getattr(pod_ctx, "labels_synced", False):
                # the context collected LOCAL labels; fold assignment
                # needs the GLOBAL vector on every process
                pod_ctx.sync_cv_labels(cv_ctx)
                pod_ctx.labels_synced = True
        return rows

    def update_states(ests, states, ds: ColumnarDataset) -> None:
        for est in ests:
            t0 = time.perf_counter()
            cols = [ds[n] for n in est.input_names]
            states[est.uid] = est.update_chunk(states[est.uid], ds, *cols)
            stage_wall[est.uid] = (stage_wall.get(est.uid, 0.0)
                                   + time.perf_counter() - t0)

    def init_states(ests) -> Dict[str, object]:
        """Fresh streaming states — or, under a refresh context, the
        restored warm-start states where still valid."""
        out: Dict[str, object] = {}
        for est in ests:
            state = (refresh_ctx.initial_state(est)
                     if refresh_ctx is not None else None)
            out[est.uid] = state if state is not None else est.begin_fit()
        return out

    final_states: Dict[str, object] = {}

    def finish_layer(ests, states) -> None:
        for est in ests:
            t0 = time.perf_counter()
            state = states[est.uid]
            # fold-tagged proxies export ONLY the full-data component as
            # warm-start capital (fold states are per-train scaffolding)
            exporter = getattr(est, "export_full_state", None)
            model = est.adopt_model(est.finish_fit(state))
            stage_wall[est.uid] = (stage_wall.get(est.uid, 0.0)
                                   + time.perf_counter() - t0)
            est._record_fit_wall(coll, stage_wall[est.uid])
            fitted_by_uid[est.uid] = model
            stage_kind[est.uid] = "fit-stream"
            # final mergeable state -> warm-start capital for refresh
            final_states[est.uid] = (exporter(state) if exporter is not None
                                     else est.export_fit_state(state))
            if refresh_ctx is not None:
                refresh_ctx.note_finished(est, model)

    def layer_ests(li: int) -> List[Estimator]:
        out = [s for s in prefix[li]
               if isinstance(s, Estimator) and s.uid not in subs]
        if cv_ctx is not None:
            out = [cv_ctx.wrap(s) for s in out]
        return out

    def ensure_cv_folds(ests) -> None:
        """Fold assignment must precede any fold-tagged update: labels
        come from the first executed reader pass, or — when the tagged
        layer fits on the FIRST pass, or a resume restored every earlier
        pass without reading — from a dedicated label pre-pass."""
        if (cv_ctx is None or cv_ctx.folds_ready
                or not cv_ctx.wraps_any(ests)):
            return
        if not cv_ctx.labels_ready:
            run_reader_pass("cv-labels", [], set(),
                            lambda ds, _i: None, keep_unknown=False)
        cv_ctx.assign_folds()

    # -- what must materialize: keep-set + the in-core tail's inputs --------
    prefix_outputs = set(out_stage)
    available = raw_names | prefix_outputs
    tail_inputs: Set[str] = set()
    for layer in tail:
        for s in layer:
            tail_inputs |= {n for n in s.input_names if n in available}
    mat_cols: Set[str] = set(tail_inputs)
    if keep is None:
        mat_cols |= available
    else:
        mat_cols |= set(keep) & available
    if cv_ctx is not None:
        # fold validation re-transforms the during DAG over fold slices:
        # its upstream inputs (+ the label) must materialize; the final
        # keep-select drops them again after validation
        mat_cols |= cv_ctx.extra_columns & available

    est_idxs = [li for li in range(len(prefix)) if layer_ests(li)]
    # everything the whole run must compute: mat_cols plus every fitting
    # estimator's inputs
    all_targets: Set[str] = set(mat_cols)
    for li in est_idxs:
        for est in layer_ests(li):
            all_targets |= set(est.input_names)
    needed_uids = _closure(sorted(all_targets), out_stage)

    # under a pod the writer holds LOCAL rows only (this process's host
    # ranges); the materialize pass gathers the pieces afterwards
    writer = _ColumnWriter(
        pod_ctx.local_rows if pod_ctx is not None else total_rows,
        shard_onto=shard_onto, shard_columns=set(shard_columns or ()))
    materialized: Dict[str, FeatureColumn] = {}

    def write_only(ds: ColumnarDataset, _idx: int) -> None:
        writer.append(ds, [c for c in ds.names()
                           if c in mat_cols or c in extras])

    def materialize_only_pass() -> int:
        """One reader pass over the (fully fitted) prefix writing every
        materialized column — the no-estimator path, the final pass of a
        checkpointed CV train whose fold-tagged layers all ran as
        dedicated checkpointable passes, and EVERY pod train's final
        pass (pod: local rows only, then the RSS probe and the
        cross-process gather)."""
        ordered = [s for layer in prefix for s in layer
                   if s.uid in needed_uids]
        try:
            rows = run_reader_pass("materialize", ordered, set(mat_cols),
                                   write_only, keep_unknown=True)
            cols = writer.finish()
            if pod_ctx is not None:
                # the POD_SMOKE memory gate's probe point: per-host peak
                # RSS BEFORE any process sees the full dataset
                pod_ctx.note_ingest_rss(ingest)
                cols = pod_ctx.gather_columns(cols)
            materialized.update(cols)
            return rows
        except BaseException:
            writer.close()   # release per-shard device buffers on abort
            raise

    def _run_fused_and_cascade(fuse_at, fuse_ests, fuse_inputs, chain,
                               run_stages, states, store, direct_cols,
                               block_cols, feed_and_capture) -> None:
        """The fused fit+materialize reader pass and the block cascade
        over the retained chunks (extracted so the deferred-fuse CV path
        can skip it wholesale)."""
        nonlocal total_rows
        try:
            names = ", ".join(_est_name(e) for e in fuse_ests)
            rows = run_reader_pass(
                f"fit+materialize[layer {fuse_at}: {names}]", run_stages,
                fuse_inputs | direct_cols | block_cols, feed_and_capture,
                keep_unknown=True)
            total_rows = rows if total_rows is None else total_rows
            writer.total = total_rows  # later-touched columns preallocate
            finish_layer(fuse_ests, states)
            ingest.spilled_bytes = store.spilled_bytes

            # -- block cascade: later estimator layers + assembly, one
            #    block at a time; the initial (possibly disk-spilled)
            #    blocks are consumed once, later segments re-retain
            #    written columns as zero-copy buffer views -----------------
            n_blocks = len(store)
            cur: object = store
            pos = 0
            while pos < len(chain):
                seg_end = pos
                seg_ests: List[Estimator] = []
                while seg_end < len(chain):
                    s = chain[seg_end]
                    if (isinstance(s, Estimator) and s.uid not in subs
                            and s.uid not in fitted_by_uid):
                        if (not seg_ests
                                or stage_layer[s.uid]
                                == stage_layer[seg_ests[0].uid]):
                            seg_ests.append(s)
                            seg_end += 1
                            continue
                        break
                    if seg_ests:
                        break
                    seg_end += 1
                segment = [s for s in chain[pos:seg_end]
                           if s not in seg_ests]
                remaining = chain[seg_end:]
                seg_inputs: Set[str] = set()
                for est in seg_ests:
                    seg_inputs |= set(est.input_names)
                retain_cols = ({n for s in remaining
                                for n in s.input_names}
                               - {s.get_output().name for s in remaining})
                # estimator outputs are only writable AFTER their fit — a
                # segment writes the columns its (already fitted) stages
                # produce; seg_ests' own outputs get written by the NEXT
                # segment once their models exist
                seg_write = (set(mat_cols)
                             & {s.get_output().name for s in segment})
                needed_after = _liveness(
                    segment, seg_inputs | retain_cols | seg_write)
                ensure_cv_folds(seg_ests)
                seg_states = init_states(seg_ests)
                apass = ingest.begin_pass(
                    "assemble" if not seg_ests else
                    "fit-blocks[layer "
                    f"{stage_layer[seg_ests[0].uid]}: "
                    + ", ".join(_est_name(e) for e in seg_ests) + "]")
                t_pass = time.perf_counter()
                nxt: List[Optional[ColumnarDataset]] = []
                offset = 0
                for bi in range(n_blocks):
                    if isinstance(cur, _BlockStore):
                        ds_b = cur.pop(bi)
                    else:
                        ds_b = cur[bi]
                        cur[bi] = None
                    n_b = len(ds_b)
                    t0 = time.perf_counter()
                    for idx, st in enumerate(segment):
                        ds_b = timed_transform(st, ds_b)
                        ds_b = ds_b.select([c for c in ds_b.names()
                                            if c in needed_after[idx]])
                    if seg_ests:
                        if cv_ctx is not None:
                            cv_ctx.set_window(offset, n_b)
                        update_states(seg_ests, seg_states, ds_b)
                    writer.offset = offset
                    writer.append(ds_b, [c for c in ds_b.names()
                                         if c in seg_write])
                    if remaining or seg_ests:
                        kept: Dict[str, FeatureColumn] = {}
                        for c in (retain_cols | seg_inputs):
                            if c not in ds_b:
                                continue
                            view = (writer.row_view(c, offset,
                                                    offset + n_b)
                                    if c in seg_write else None)
                            kept[c] = view if view is not None else ds_b[c]
                        nxt.append(ColumnarDataset(kept, _validated=True))
                    offset += n_b
                    apass.note_read(n_b, 0.0, 0)
                    apass.note_transform(bi, time.perf_counter() - t0)
                apass.wall_s = time.perf_counter() - t_pass
                cur = nxt
                if seg_ests:
                    finish_layer(seg_ests, seg_states)
                    # re-visit the just-fitted estimators: their MODELS
                    # are runnable transforms for the next segment
                    pos = seg_end - len(seg_ests)
                else:
                    pos = seg_end
        except BaseException:
            writer.close()   # release per-shard device buffers on abort
            raise
        finally:
            store.close()

    # est_idxs is derived from the pipeline STRUCTURE, identical on
    # every pod process — both arms run the same collective schedule
    if not est_idxs:  # tmog: disable=TM071
        # no estimators in the prefix: a single materialize pass
        materialize_only_pass()
    else:
        # fuse at the SECOND estimator layer when there is one (its pass
        # can already compute the first layer's model outputs, so the
        # retained blocks are derived, compact columns); a single
        # estimator layer fuses on its own pass.  CHECKPOINTED CV trains
        # defer the fuse past the last fold-tagged layer: the fused
        # fit+materialize pass is deliberately not mid-pass-checkpointed
        # (its progress is the output buffers), so fold-tagged layers run
        # as dedicated checkpointable passes instead — one extra reader
        # pass buys a bit-exact mid-fold resume (fuse_at=None = every
        # estimator layer is a plain pass + a final materialize pass).
        fuse_at: Optional[int] = (est_idxs[1] if len(est_idxs) >= 2
                                  else est_idxs[0])
        if cv_ctx is not None and manager is not None:
            tagged = [li for li in est_idxs
                      if cv_ctx.wraps_any(layer_ests(li))]
            if tagged:
                later = [li for li in est_idxs if li > max(tagged)]
                fuse_at = later[0] if later else None
        if pod_ctx is not None:
            # pod trains always run the pass-structured shape: every
            # estimator layer is a plain (exchange-mergeable,
            # checkpointable) pass + one final materialize pass — the
            # fused pass's block cascade is a single-process optimization
            # whose retained blocks cannot allgather incrementally
            fuse_at = None

        # plain reader fit passes for estimator layers before the fuse —
        # the checkpointable passes: their whole progress is the mergeable
        # states + a chunk cursor (workflow/checkpoint.py)
        prefuse = [li for li in est_idxs
                   if fuse_at is None or li < fuse_at]
        for pass_idx, li in enumerate(prefuse):
            ests = layer_ests(li)
            names = ", ".join(_est_name(e) for e in ests)
            label = f"fit[layer {li}: {names}]"
            if resume is not None and pass_idx in resume.completed:
                # pass-boundary resume: adopt the persisted models, never
                # re-read the data for this layer
                import copy as _copy

                from .checkpoint import (CheckpointMismatchError,
                                         adopt_restored_model)

                done = resume.completed[pass_idx]
                for est in ests:
                    model = done["models"].get(est.uid)
                    if model is None:
                        raise CheckpointMismatchError(
                            f"checkpoint pass {pass_idx} is missing a "
                            f"model for estimator {est.uid}")
                    inner = getattr(est, "inner", est)
                    fitted_by_uid[est.uid] = adopt_restored_model(inner,
                                                                  model)
                    stage_kind[est.uid] = "fit-restored"
                    payload = (done.get("states") or {}).get(est.uid)
                    if payload is not None:
                        # fold-tagged layer: re-import the persisted
                        # final state so the CV validation still has its
                        # per-fold states (deep copy — the manager's
                        # carried payloads re-encode on the next save)
                        st = est.import_fit_state(_copy.deepcopy(payload))
                        if (cv_ctx is not None
                                and hasattr(est, "export_full_state")):
                            cv_ctx.note_fold_states(inner, st.folds)
                        final_states.setdefault(
                            est.uid,
                            inner.export_fit_state(st.full)
                            if hasattr(est, "export_full_state")
                            else est.export_fit_state(st))
                if total_rows is None:
                    total_rows = done["rows"]
                continue
            target_inputs: Set[str] = set()
            for est in ests:
                target_inputs |= set(est.input_names)
            pass_uids = _closure(sorted(target_inputs), out_stage)
            ordered = [s for lj in range(li) for s in prefix[lj]
                       if s.uid in pass_uids]
            ensure_cv_folds(ests)
            # pod_ctx is non-None iff pod.active — uniform across the
            # pod, so every process picks the same fit-pass flavour
            if pod_ctx is not None:  # tmog: disable=TM071
                # -- pod fit pass: per-entry partial states, barrier-
                #    fenced mid-pass saves, allgather merge at the end --
                use_resume = (pod_ctx.resume_pass == pass_idx)
                decode = (resume.decode_payload
                          if (resume is not None and use_resume) else None)
                entry_states = pod_ctx.init_entry_states(
                    ests, decode, use_initial=use_resume)
                pod_skips = ([e.skip_chunks for e in pod_ctx.entries]
                             if use_resume else None)
                saver = pod_ctx.pass_saver(manager, pass_idx, label,
                                           ests, entry_states)
                cur_entry = {"pos": 0}

                def pod_update(ds, _i, _es=ests, _st=entry_states,
                               _c=cur_entry):
                    update_states(_es, _st[_c["pos"]], ds)

                def on_pod_chunk(pos, done, _s=saver):
                    if _s is not None:
                        _s.note_chunk(pos, done)

                run_reader_pass(
                    label, ordered, set(target_inputs), pod_update,
                    keep_unknown=False, pod_skips=pod_skips,
                    on_pod_entry=lambda pos, _c=cur_entry:
                        _c.__setitem__("pos", pos),
                    on_pod_chunk=on_pod_chunk)
                if saver is not None:
                    saver.drain()
                states = pod_ctx.merge_pass_states(ests, entry_states)
                finish_layer(ests, states)
                if manager is not None:
                    t0 = time.perf_counter()
                    pod_ctx.complete_pass(
                        manager, pass_idx, label,
                        {est.uid: fitted_by_uid[est.uid] for est in ests},
                        state_payloads={
                            est.uid: est.export_fit_state(states[est.uid])
                            for est in ests
                            if hasattr(est, "export_full_state")})
                    _note_checkpoint(t0)
                continue
            states = init_states(ests)
            skip = 0
            if (resume is not None and resume.current is not None
                    and int(resume.current["pass"]) == pass_idx):
                # mid-pass resume: bit-exact states + fast-skip cursor
                states = resume.states_for(ests)
                skip = int(resume.current["chunks_done"])
            on_chunk = None
            if manager is not None:
                def on_chunk(ci, rows_done, _pi=pass_idx, _lb=label,
                             _e=ests, _st=states):
                    if (ci + 1) % manager.every_chunks == 0:
                        t0 = time.perf_counter()
                        manager.save_progress(_pi, _lb, ci + 1, rows_done,
                                              _e, _st)
                        _note_checkpoint(t0)
            rows = run_reader_pass(
                label, ordered, set(target_inputs),
                lambda ds, _i, e=ests, st=states: update_states(e, st, ds),
                keep_unknown=False, skip_chunks=skip, on_chunk=on_chunk)
            total_rows = rows if total_rows is None else total_rows
            finish_layer(ests, states)
            if manager is not None:
                t0 = time.perf_counter()
                manager.complete_pass(
                    pass_idx, label, rows,
                    {est.uid: fitted_by_uid[est.uid] for est in ests},
                    state_payloads={
                        est.uid: est.export_fit_state(states[est.uid])
                        for est in ests
                        if hasattr(est, "export_full_state")})
                _note_checkpoint(t0)

        # fuse_at depends only on the pipeline layout + CV config, both
        # identical on every pod process
        if fuse_at is None:  # tmog: disable=TM071
            # every estimator layer ran as a checkpointable plain pass
            # (the deferred-fuse CV+checkpoint path, and every pod
            # train): one final materialize pass over the fully fitted
            # prefix
            writer.total = (pod_ctx.local_rows if pod_ctx is not None
                            else total_rows)
            materialize_only_pass()
            chain_outputs: Set[str] = set()
        else:
            # -- fused retention pass at ``fuse_at`` -----------------------
            fuse_ests = layer_ests(fuse_at)
            fuse_uids = {e.uid for e in fuse_ests}
            fuse_inputs: Set[str] = set()
            for est in fuse_ests:
                fuse_inputs |= set(est.input_names)

            # forward reachability from every not-yet-fitted estimator at
            # or after the fuse point: those form the block-cascade chain
            pending_est_uids = {e.uid for li in est_idxs if li >= fuse_at
                                for e in layer_ests(li)}
            down_out_names = {e.get_output().name for e in fuse_ests}
            chain_tail: List[PipelineStage] = []
            for lj in range(fuse_at, len(prefix)):
                for s in prefix[lj]:
                    if s.uid in fuse_uids or s.uid not in needed_uids:
                        continue
                    if (s.uid in pending_est_uids
                            or any(n in down_out_names
                                   for n in s.input_names)):
                        chain_tail.append(
                            cv_ctx.wrap(s) if (cv_ctx is not None
                                               and isinstance(s, Estimator))
                            else s)
                        down_out_names.add(s.get_output().name)
            consumed = set(mat_cols) | {
                n for s in chain_tail for n in s.input_names}
            chain: List[PipelineStage] = (
                [e for e in fuse_ests if e.get_output().name in consumed]
                + chain_tail)
            chain_uids = {s.uid for s in chain}
            chain_outputs = {s.get_output().name for s in chain}
            block_cols = ({n for s in chain for n in s.input_names}
                          - chain_outputs)
            direct_cols = set(mat_cols) - chain_outputs

            run_stages = [s for layer in prefix for s in layer
                          if s.uid in needed_uids and s.uid not in chain_uids
                          and s.uid not in fuse_uids]
            ensure_cv_folds(fuse_ests)
            states = init_states(fuse_ests)
            store = _BlockStore(_retain_budget_bytes(retain_mb))

            def feed_and_capture(ds: ColumnarDataset, _idx: int) -> None:
                update_states(fuse_ests, states, ds)
                writer.append(ds, [c for c in ds.names()
                                   if c in direct_cols or c in extras])
                if chain:
                    store.append(ds.select([c for c in block_cols
                                            if c in ds]))

            _run_fused_and_cascade(
                fuse_at, fuse_ests, fuse_inputs, chain, run_stages, states,
                store, direct_cols, block_cols, feed_and_capture)
            missing = (set(mat_cols) & chain_outputs) - set(writer.cols)
            if missing:  # pragma: no cover - cascade covers chain outputs
                raise RuntimeError(
                    f"block cascade failed to materialize {sorted(missing)}")
            try:
                materialized.update(writer.finish())
            except BaseException:
                writer.close()
                raise

    data = ColumnarDataset(materialized, _validated=True)

    # -- workflow-CV validation (between prefix and tail): per-fold models
    #    from merged fold-tagged states, the selector sweep over the fold
    #    matrices, best_estimator set so the tail's fit skips validation --
    if cv_ctx is not None:
        cv_ctx.run_validation(data)

    # fitted stages in topo order: prefix (transformers are their own
    # fitted stage, matching the in-core executor's returned list)
    fitted: List[PipelineStage] = []
    for layer in prefix:
        for s in layer:
            if isinstance(s, Estimator):
                fitted.append(fitted_by_uid.get(s.uid) or subs[s.uid])
                stage_kind.setdefault(s.uid, "substitute")
            else:
                fitted.append(s)
                stage_kind.setdefault(s.uid, "transform-stream")

    if total_rows is None:
        total_rows = len(data)
    if profiler is not None:
        from ..utils.profiling import backend_name, mesh_desc

        for s in (st for layer in prefix for st in layer):
            op = type(s).__name__
            kind = stage_kind.get(s.uid, "transform-stream")
            width = sum(1 for _ in s.input_names) or 1
            dtype = ""
            for n in s.input_names:
                if n in data:
                    v = data[n].values
                    shape = getattr(v, "shape", None)
                    if getattr(v, "ndim", 1) >= 2 and shape:
                        width += int(shape[1]) - 1
                    if not dtype:
                        dtype = str(getattr(v, "dtype", "") or "")
            n_dev, mshape = mesh_desc(getattr(s, "mesh", None))
            profiler.record_stage(StageProfile(
                uid=s.uid, op=op,
                output=s.get_output().name,
                layer=stage_layer.get(s.uid, 0),
                kind=kind,
                device_heavy=s.device_heavy,
                wall_s=stage_wall.get(s.uid, 0.0),
                rows=total_rows or 0, cols_added=1,
                cols=width, dtype=dtype, backend=backend_name(),
                stage_kind=f"{op}:{kind}",
                n_devices=n_dev, mesh_shape=mshape))
        profiler.note_columns(len(data.columns))

    # -- tail: non-streamable suffix runs in-core on the packed dataset ----
    if tail:
        tail_dag = StagesDAG(tail)
        fitted_tail, data, _ = fit_and_transform_dag(
            tail_dag, data, fitted_substitutes=subs, keep=keep,
            profiler=profiler)
        fitted.extend(fitted_tail)

    if keep is not None:
        # parity with the in-core plan's final state: keep-set columns plus
        # plan-unknown passthroughs (e.g. a reader's "key")
        keep_set = set(keep)
        data = data.select([c for c in data.names()
                            if c in keep_set or c not in known_universe])
    if pod_ctx is not None:
        # coordinator lands every process's buffered quarantine entries
        # in the ONE sidecar; doubles as the train-end sync point
        pod_ctx.flush_quarantine(sink)
        if ingest.pod is None:
            ingest.pod = pod_ctx.to_json()
    if sink is not None:
        ingest.quarantined_records = sink.count - q0_records
        ingest.quarantined_rows = sink.rows - q0_rows
    if manager is not None:
        # success: a finished train's checkpoint must not resurrect into
        # the next run in the same directory
        manager.finish()
    return fitted, data, ingest, final_states
