"""Fold-tagged streaming CV — workflow-level cross-validation out of core.

The in-core workflow-CV path (``OpWorkflow.with_workflow_cv``) cuts the
DAG at the ModelSelector and REFITS the label-leaking "during" segment
(SanityChecker, supervised bucketizers) inside every fold
(``OpValidator.applyDAG``).  That refit-per-fold re-reads the training
data K times — exactly what an out-of-core train cannot do.

The streaming substitute exploits what the streaming-fit protocol already
guarantees: per-estimator states are MERGEABLE MONOIDS.  Fold ids are
assigned per GLOBAL row id (``selector.validators.make_folds`` over the
splitter's train subset — the same seeded assignment the in-core
validator makes, so chunking is invariant), every during-DAG estimator's
``update_chunk`` additionally accumulates ONE STATE PER FOLD, and the
fold-k refit model is ``finish_fit(merge(states[j] for j != k))`` — the
fold-complement fit without a single extra reader pass.  The per-fold
metrics then come from transforming the materialized fold slices through
the during DAG with the fold models substituted, byte-for-byte the same
candidate fitters the in-core sweep runs (contract TM029 property-checks
the fold-merge equivalence; the per-fold outputs match the in-core
refit within each stage's declared ``streaming_fit_tol``).

Fault points: ``cv.fold`` fires once per fold context as its matrices
build (``index`` = fold ordinal) — a ``raise`` here exercises a fold
that cannot evaluate; the selector sweep itself runs through the
ordinary ``SweepWorkQueue`` (mid-sweep checkpoint cursor + elastic
device-loss ladder both armed when configured).
"""
from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..stages.base import Estimator, Model
from ..utils import faults

__all__ = ["StreamingCVContext", "FoldTaggedState", "FoldTaggedEstimator"]


class FoldTaggedState:
    """One streamed estimator's CV-aware fit state: the FULL-data state
    (the model the DAG adopts) plus one mergeable state per fold of the
    splitter's train subset (holdout rows ride only the full state)."""

    __slots__ = ("full", "folds")

    def __init__(self, full, folds: List[Any]):
        self.full = full
        self.folds = folds


#: checkpoint-codec marker for a fold-tagged state payload
_TAG = "__fold_tagged__"


class FoldTaggedEstimator(Estimator):
    """Streaming-protocol proxy that accumulates fold-tagged states.

    Wraps a during-DAG estimator for the out-of-core driver: every
    ``update_chunk`` updates the full-data state with the whole chunk
    (chunk order preserved — parity with a plain streaming train) and
    each fold's state with that fold's rows (row→fold via the context's
    global assignment, so the accumulation is chunking-invariant).  The
    wrapped estimator's own ``export/import_fit_state`` hooks carry each
    component through the checkpoint codec — a mid-pass kill restores
    every fold state bit-exactly.
    """

    # deliberately no super().__init__: the proxy answers for the inner
    # stage's identity (uid/wiring) rather than minting its own
    def __init__(self, inner: Estimator, ctx: "StreamingCVContext"):
        self.inner = inner
        self.ctx = ctx
        self.uid = inner.uid
        self.operation_name = inner.operation_name
        self.output_type = inner.output_type
        self.input_features = inner.input_features
        self._output_feature = inner._output_feature
        self.metadata = inner.metadata

    # -- identity delegation -------------------------------------------------

    @property
    def supports_streaming_fit(self) -> bool:
        return bool(self.inner.supports_streaming_fit)

    @property
    def streaming_fit_tol(self) -> float:
        return float(self.inner.streaming_fit_tol)

    @property
    def device_heavy(self) -> bool:
        return self.inner.device_heavy

    def adopt_model(self, model: Model) -> Model:
        return self.inner.adopt_model(model)

    def _record_fit_wall(self, coll, dt: float) -> None:
        self.inner._record_fit_wall(coll, dt)

    # -- fold-tagged streaming protocol --------------------------------------

    def begin_fit(self) -> FoldTaggedState:
        k = self.ctx.num_folds
        return FoldTaggedState(self.inner.begin_fit(),
                               [self.inner.begin_fit() for _ in range(k)])

    def update_chunk(self, state: FoldTaggedState, data, *cols
                     ) -> FoldTaggedState:
        state.full = self.inner.update_chunk(state.full, data, *cols)
        g = self.ctx.window_folds(len(data))
        for k in range(self.ctx.num_folds):
            idx = np.where(g == k)[0]
            if not len(idx):
                continue
            sub = data.take(idx)
            sub_cols = [sub[n] for n in self.inner.input_names]
            state.folds[k] = self.inner.update_chunk(
                state.folds[k], sub, *sub_cols)
        return state

    def merge_states(self, a: FoldTaggedState,
                     b: FoldTaggedState) -> FoldTaggedState:
        return FoldTaggedState(
            self.inner.merge_states(a.full, b.full),
            [self.inner.merge_states(x, y)
             for x, y in zip(a.folds, b.folds)])

    def finish_fit(self, state: FoldTaggedState) -> Model:
        # the fold states are the CV capital — hand them to the context
        # BEFORE finish_fit (implementations may finalize in place)
        self.ctx.note_fold_states(self.inner, state.folds)
        return self.inner.finish_fit(state.full)

    # -- checkpoint codec hooks ----------------------------------------------

    def export_fit_state(self, state: FoldTaggedState):
        return {_TAG: True,
                "full": self.inner.export_fit_state(state.full),
                "folds": [self.inner.export_fit_state(s)
                          for s in state.folds]}

    def export_full_state(self, state: FoldTaggedState):
        """The FULL-data component only — what rides on the model as
        ``fit_states`` (the warm-start capital a refresh resumes from;
        fold states are per-train scaffolding, not model state)."""
        return self.inner.export_fit_state(state.full)

    def import_fit_state(self, payload) -> FoldTaggedState:
        if isinstance(payload, dict) and payload.get(_TAG):
            return FoldTaggedState(
                self.inner.import_fit_state(payload["full"]),
                [self.inner.import_fit_state(p)
                 for p in payload["folds"]])
        # a PLAIN payload (a refresh warm-starting from the base model's
        # exported full state): the full state resumes, fold states
        # accumulate from this run's window alone
        return FoldTaggedState(
            self.inner.import_fit_state(payload),
            [self.inner.begin_fit() for _ in range(self.ctx.num_folds)])


class StreamingCVContext:
    """Fold bookkeeping + validation orchestration for ONE streaming
    workflow-CV train (built by ``OpWorkflow._train_chunked`` from the
    CV cut, consumed by ``workflow.streaming.fit_dag_streaming``)."""

    def __init__(self, selector, during_dag, subs: Dict[str, Model]):
        self.selector = selector
        self.during_dag = during_dag
        self.subs = dict(subs or {})
        during = [s for layer in during_dag.layers for s in layer]
        self.during_uids: Set[str] = {
            s.uid for s in during
            if isinstance(s, Estimator) and s.uid not in self.subs}
        outputs = {s.get_output().name for s in during}
        #: during-DAG inputs produced UPSTREAM (before-DAG / raw) — these
        #: must materialize so fold slices can re-transform per fold
        self.extra_columns: Set[str] = {
            n for s in during for n in s.input_names} - outputs
        self.label_name = selector.label_feature.name
        self.features_name = selector.features_feature.name
        self.extra_columns.add(self.label_name)

        v = selector.validator
        from ..selector.validators import OpTrainValidationSplit

        self._is_split = isinstance(v, OpTrainValidationSplit)
        self.num_folds = 2 if self._is_split else int(v.num_folds)

        self._wrapped: Dict[str, FoldTaggedEstimator] = {}
        self._label_parts: List[np.ndarray] = []
        self.labels_ready = False
        self.folds_ready = False
        self.y: Optional[np.ndarray] = None
        self._global_folds: Optional[np.ndarray] = None
        self._train_idx: Optional[np.ndarray] = None
        self._folds_sub: Optional[np.ndarray] = None
        self._base_w: Optional[np.ndarray] = None
        self._win: Tuple[int, int] = (0, 0)
        self._fold_states: Dict[str, List[Any]] = {}
        self.validated = False

    # -- fingerprint (checkpoint fold-geometry guard) ------------------------

    def fingerprint(self) -> Dict[str, Any]:
        """The LOGICAL fold geometry a streaming-CV checkpoint is pinned
        to: resuming with different folds/seed/stratification must refuse
        (``CheckpointMismatchError`` with the key-level diff) — the fold
        states in the checkpoint were accumulated under THIS assignment.
        Mesh shape stays out of it (advisory, PR 9 split)."""
        v = self.selector.validator
        return {"cv": {
            "validator": type(v).__name__,
            "numFolds": None if self._is_split else int(v.num_folds),
            "trainRatio": (float(v.train_ratio) if self._is_split
                           else None),
            "seed": int(v.seed),
            "stratify": bool(getattr(v, "stratify", False)),
        }}

    # -- label collection (first reader pass) --------------------------------

    @property
    def collecting_labels(self) -> bool:
        return not self.labels_ready

    def begin_label_pass(self) -> None:
        if not self.labels_ready:
            self._label_parts = []

    def collect_labels(self, chunk) -> None:
        if self.labels_ready or self.label_name not in chunk:
            return
        self._label_parts.append(np.nan_to_num(np.asarray(
            chunk[self.label_name].values, np.float64)))

    def finish_label_pass(self, rows: int) -> None:
        if self.labels_ready:
            return
        got = sum(len(p) for p in self._label_parts)
        if got != rows:  # pragma: no cover - label is a raw column
            raise RuntimeError(
                f"workflow CV could not collect the label column "
                f"{self.label_name!r} over the reader pass "
                f"({got} of {rows} rows)")
        self.y = (np.concatenate(self._label_parts) if self._label_parts
                  else np.zeros(0))
        self._label_parts = []
        self.labels_ready = True

    # -- fold assignment (global row ids) ------------------------------------

    def assign_folds(self) -> None:
        """Fold id per GLOBAL row, mirroring the in-core
        ``find_best_estimator`` exactly: the splitter reserves the
        holdout and weights the train subset, then folds are made over
        the train subset with the validator's seed/stratification.
        Rows outside the train subset get fold -1 (full state only)."""
        if self.folds_ready:
            return
        if not self.labels_ready:  # pragma: no cover - driver orders this
            raise RuntimeError("fold assignment before label collection")
        from ..selector.validators import make_folds

        y = self.y
        n = len(y)
        self.selector._capture_class_space(y)
        splitter = self.selector._resolved_splitter()
        train_idx, _ = splitter.split_indices(n, y)
        train_mask = np.zeros(n, dtype=bool)
        train_mask[train_idx] = True
        self._base_w = splitter.train_weights(y, train_mask)
        v = self.selector.validator
        if self._is_split:
            in_train = v._split_mask(len(train_idx), y[train_idx])
            folds_sub = np.where(in_train, 1, 0).astype(np.int32)
        else:
            folds_sub = make_folds(len(train_idx), v.num_folds,
                                   y=y[train_idx],
                                   stratify=v.stratify, seed=v.seed)
        g = np.full(n, -1, dtype=np.int32)
        g[train_idx] = folds_sub
        self._train_idx = train_idx
        self._folds_sub = folds_sub
        self._global_folds = g
        self.folds_ready = True

    # -- driver hooks --------------------------------------------------------

    def wrap(self, est: Estimator) -> Estimator:
        """The fold-tagged proxy for a during-DAG estimator (memoized so
        every driver code path sees ONE object per uid)."""
        if est.uid not in self.during_uids:
            return est
        got = self._wrapped.get(est.uid)
        if got is None:
            got = self._wrapped[est.uid] = FoldTaggedEstimator(est, self)
        return got

    def wraps_any(self, ests: Sequence[Estimator]) -> bool:
        return any(isinstance(e, FoldTaggedEstimator) for e in ests)

    def set_window(self, start_row: int, n_rows: int) -> None:
        self._win = (int(start_row), int(n_rows))

    def window_folds(self, n: int) -> np.ndarray:
        if not self.folds_ready:  # pragma: no cover - driver orders this
            raise RuntimeError("fold-tagged update before fold assignment")
        start, wn = self._win
        if n != wn:  # pragma: no cover - transforms are row-preserving
            raise RuntimeError(
                f"fold window desync: chunk has {n} rows, window {wn}")
        return self._global_folds[start:start + n]

    def note_fold_states(self, inner: Estimator, folds: List[Any]) -> None:
        self._fold_states[inner.uid] = folds

    # -- the CV validation (between prefix and tail) -------------------------

    def _fold_model(self, inner: Estimator, train_folds: Sequence[int]
                    ) -> Model:
        """finish_fit(merge of the complement's fold states) wired as a
        standalone transform — the estimator's live metadata (written by
        the FULL-data finish that already ran) is shielded from the fold
        finishes, matching the in-core order where the full fit lands
        last."""
        states = self._fold_states[inner.uid]
        parts = [copy.deepcopy(states[j]) for j in train_folds]
        merged = parts[0]
        for p in parts[1:]:
            merged = inner.merge_states(merged, p)
        saved = inner.metadata
        inner.metadata = {}
        try:
            model = inner.finish_fit(merged)
            model.uid = inner.uid
            model.operation_name = inner.operation_name
            model.input_features = list(inner.input_features)
            model._output_feature = inner._output_feature
            model.metadata = inner.metadata
        finally:
            inner.metadata = saved
        return model

    def _fold_matrices(self, data, tr_idx: np.ndarray, ev_idx: np.ndarray,
                       fold_subs: Dict[str, Model]):
        """The streaming analogue of ``_ValidatorBase._fold_matrices``:
        same plan-bounded gathers, same matrix extraction — but the
        during-DAG estimators are SUBSTITUTED with fold-complement models
        instead of refit from the rows."""
        from .dag import fit_and_transform_dag, sequential_executor_forced
        from .plan import plan_for

        keep = [self.features_name, self.label_name]
        if sequential_executor_forced():
            train_ds = data.take(tr_idx)
            eval_ds = data.take(ev_idx)
            _, train_t, eval_t = fit_and_transform_dag(
                self.during_dag, train_ds, apply_to=eval_ds,
                fitted_substitutes=fold_subs, sequential=True)
        else:
            plan = plan_for(self.during_dag, keep=keep)
            req = plan.required_input_columns()
            base = data.select([n for n in data.names() if n in req])
            train_ds = base.take(tr_idx)
            eval_ds = base.take(ev_idx)
            _, train_t, eval_t = fit_and_transform_dag(
                self.during_dag, train_ds, apply_to=eval_ds,
                fitted_substitutes=fold_subs, keep=keep)
        X_tr = np.ascontiguousarray(np.asarray(
            train_t[self.features_name].values, dtype=np.float32))
        X_ev = np.ascontiguousarray(np.asarray(
            eval_t[self.features_name].values, dtype=np.float32))
        y_tr = np.nan_to_num(np.asarray(
            train_t[self.label_name].values, dtype=np.float32))
        y_ev = np.nan_to_num(np.asarray(
            eval_t[self.label_name].values, dtype=np.float32))
        return X_tr, y_tr, X_ev, y_ev

    def fold_contexts(self) -> List[Tuple[Tuple[int, ...], int]]:
        """(train_folds, eval_fold) per validation context: K
        leave-one-out complements for CV, the single (train side, eval
        side) pair for a train/validation split."""
        if self._is_split:
            return [((1,), 0)]
        k = self.num_folds
        return [(tuple(j for j in range(k) if j != fold), fold)
                for fold in range(k)]

    def run_validation(self, data) -> None:
        """Build the per-fold matrices from merged fold states and run
        the selector sweep — sets ``selector.best_estimator`` so the
        in-core tail's fit consumes the winner without re-validating
        (the exact contract of the in-core ``find_best_estimator``)."""
        if self.validated:
            return
        self.assign_folds()
        missing = self.during_uids - set(self._fold_states)
        if missing:  # pragma: no cover - driver fits the whole prefix
            raise RuntimeError(
                f"workflow CV reached validation with unfitted during-DAG "
                f"estimators: {sorted(missing)}")
        per_fold = []
        for ci, (train_folds, eval_fold) in enumerate(self.fold_contexts()):
            faults.fire("cv.fold", index=ci)
            tr_pos = np.isin(self._folds_sub, train_folds)
            ev_pos = self._folds_sub == eval_fold
            tr_idx = self._train_idx[tr_pos]
            ev_idx = self._train_idx[ev_pos]
            if not len(tr_idx) or not len(ev_idx):
                continue
            w_tr = self._base_w[tr_idx]
            w_ev = self._base_w[ev_idx]
            if w_tr.sum() == 0 or w_ev.sum() == 0:
                continue
            fold_subs = dict(self.subs)
            for uid in self.during_uids:
                inner = self._wrapped[uid].inner
                fold_subs[uid] = self._fold_model(inner, train_folds)
            X_tr, y_tr, X_ev, y_ev = self._fold_matrices(
                data, tr_idx, ev_idx, fold_subs)
            per_fold.append((X_tr, y_tr, w_tr, X_ev, y_ev, w_ev))
        if not per_fold:
            raise RuntimeError(
                "workflow CV produced no usable fold contexts "
                "(every fold had an empty or zero-weight side)")
        self.selector.find_best_estimator_prefold(
            per_fold, y=self.y, n_rows=len(self._train_idx))
        self.validated = True
