"""Workflow engine — the user-facing train/score orchestration.

Reference: ``OpWorkflow`` (core/.../OpWorkflow.scala — train :347, fitStages
:376-455, generateRawData :235), ``OpWorkflowModel`` (OpWorkflowModel.scala —
score :259, evaluate :324, summary :187-221, save :223), shared core state
``OpWorkflowCore`` (OpWorkflowCore.scala:53-324).

The TPU substitution: rather than launching Spark jobs per estimator, the DAG
executes in-process — host columnar transforms feed a device-resident feature
matrix, and every estimator's fit is a compiled XLA program.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..evaluators.evaluators import OpEvaluatorBase
from ..features.feature import Feature
from ..readers.base import Reader, reader_for
from ..stages.base import Estimator, Model, PipelineStage, Transformer
from ..stages.generator import FeatureGeneratorStage
from ..types.columns import ColumnarDataset
from .dag import (StagesDAG, compute_dag, cut_dag_cv, fit_and_transform_dag,
                  transform_dag)

__all__ = ["OpWorkflow", "OpWorkflowModel"]


class _WorkflowCore:
    """State shared by workflow and fitted model (OpWorkflowCore parity)."""

    def __init__(self):
        self.result_features: List[Feature] = []
        self.reader: Optional[Reader] = None
        self.blocklisted: List[str] = []
        self.parameters: Dict[str, Dict[str, Any]] = {}

    def set_reader(self, reader) -> "_WorkflowCore":
        self.reader = reader_for(reader)
        return self

    def set_input_data(self, data) -> "_WorkflowCore":
        """Ad-hoc dataset wrapped into a reader (setInputDataset parity)."""
        self.reader = reader_for(data)
        return self

    def raw_features(self) -> List[Feature]:
        out: List[Feature] = []
        seen = set()
        for rf in self.result_features:
            for f in rf.raw_features():
                if f.uid not in seen:
                    seen.add(f.uid)
                    out.append(f)
        return out

    def generate_raw_data(self) -> ColumnarDataset:
        if self.reader is None:
            raise RuntimeError("no reader set — call set_reader/set_input_data")
        return self.reader.generate_dataset(self.raw_features())


class OpWorkflow(_WorkflowCore):
    def __init__(self):
        super().__init__()
        self._raw_feature_filter = None
        self._model_stages: Dict[str, Model] = {}
        self._workflow_cv = False
        self._allow_non_serializable = False
        self.mesh = None

    def allow_non_serializable(self) -> "OpWorkflow":
        """Opt out of the train-time serializability gate: train with
        lambda/callable stage params anyway (saving will stub them with a
        warning; the loaded model falls back to default behavior)."""
        self._allow_non_serializable = True
        return self

    def with_mesh(self, mesh) -> "OpWorkflow":
        """Train the WHOLE workflow on a device mesh: every mesh-capable
        stage in the DAG (SanityChecker stats, the ModelSelector sweep and
        refit, each tree/linear trainer) receives the mesh at train time —
        the equivalent of the reference distributing every fit over Spark
        executors (SURVEY §2.12 row 1)."""
        self.mesh = mesh
        return self

    # -- wiring -------------------------------------------------------------

    def set_result_features(self, *features: Feature) -> "OpWorkflow":
        self.result_features = list(features)
        return self

    def set_parameters(self, params: Dict[str, Dict[str, Any]]) -> "OpWorkflow":
        """Per-stage param injection by class name or uid (OpParams parity,
        OpWorkflow.setStageParameters OpWorkflow.scala:179-201)."""
        self.parameters = dict(params)
        return self

    def with_raw_feature_filter(self, **kwargs) -> "OpWorkflow":
        """Enable RawFeatureFilter (OpWorkflow.withRawFeatureFilter :537)."""
        from ..filters.raw_feature_filter import RawFeatureFilter

        self._raw_feature_filter = RawFeatureFilter(**kwargs)
        return self

    def with_workflow_cv(self) -> "OpWorkflow":
        """Move label-aware feature-engineering estimators inside the CV
        loop (OpWorkflow.withWorkflowCV; SURVEY §3.2): the DAG is cut at the
        ModelSelector and the leakage-prone segment refits per fold."""
        self._workflow_cv = True
        return self

    def with_model_stages(self, model: "OpWorkflowModel") -> "OpWorkflow":
        """Warm-start: reuse fitted models for matching estimator uids
        (OpWorkflow.withModelStages OpWorkflow.scala:468)."""
        for s in model.stages:
            if isinstance(s, Model):
                self._model_stages[s.uid] = s
        return self

    # -- training -----------------------------------------------------------

    def _inject_params(self, dag: StagesDAG) -> None:
        if not self.parameters:
            return
        for stage in dag.all_stages():
            for key in (stage.uid, type(stage).__name__):
                if key in self.parameters:
                    stage.set_params(**self.parameters[key])

    def _apply_blocklist(self, dropped: Sequence[str]) -> None:
        """Prune dropped raw features out of stage inputs
        (OpWorkflow.setBlocklist semantics): variadic stages simply lose the
        input; a stage whose inputs all drop propagates the drop; a result
        feature that becomes unreachable is an error."""
        if not dropped:
            return
        self.blocklisted = list(dropped)
        gone = set(dropped)
        dag = compute_dag(self.result_features)
        for layer in dag.layers:
            for stage in layer:
                if isinstance(stage, FeatureGeneratorStage):
                    continue
                remaining = [f for f in stage.input_features
                             if f.name not in gone]
                if len(remaining) == len(stage.input_features):
                    continue
                lo, _ = stage.input_arity
                out = stage.get_output()
                if remaining and len(remaining) >= max(lo, 1):
                    stage.input_features = remaining
                    out.parents = list(remaining)
                else:
                    gone.add(out.name)
        bad = [f.name for f in self.result_features if f.name in gone]
        if bad:
            raise ValueError(
                f"RawFeatureFilter dropped features required by result "
                f"features {bad}; protect them via protected_features")

    def _train_keep_columns(self) -> List[str]:
        """Columns ``train()`` must retain through the DAG run — everything
        else is liveness-pruned by the execution plan as soon as its last
        consumer stage has run.  Kept: the result features, the raw
        response(s) (evaluation + ModelInsights label summary), and the
        result stages' direct inputs (the selector's feature vector backs
        ModelInsights/train_data introspection)."""
        keep = {f.name for f in self.result_features}
        keep |= {f.name for f in self.raw_features() if f.is_response}
        for f in self.result_features:
            s = f.origin_stage
            if s is not None:
                keep |= {ff.name for ff in s.input_features}
        return sorted(keep)

    def train(self, profile: bool = False,
              chunk_rows: Optional[int] = None,
              prefetch_chunks: int = 2,
              validate: bool = True,
              checkpoint_dir: Optional[str] = None,
              checkpoint_every_chunks: int = 16,
              tuner=None) -> "OpWorkflowModel":
        """Fit the workflow.  ``profile=True`` additionally records a
        per-stage execution profile (wall time, rows, columns
        added/dropped, device launches) on the returned model as
        ``train_profile`` (a PlanProfiler; ``.format()`` for the summary,
        ``.to_json()`` for the raw numbers).

        ``validate=True`` (default) runs the static DAG lint
        (analysis/linter.py — dangling/shadowed/duplicate columns,
        feature-type mismatches, label leakage) before any stage fits and
        raises :class:`~transmogrifai_tpu.analysis.PipelineLintError` on
        error-severity findings; warnings (e.g. dead stages) are recorded
        on the returned model as ``lint_snapshot`` together with the lint
        wall time.  The lint is pure graph traversal — sub-millisecond on
        the demo DAGs, <1% of train wall by bench contract.

        ``chunk_rows=k`` switches to the OUT-OF-CORE path
        (workflow/streaming.py): the reader streams bounded k-row chunks,
        streamable estimators fit via mergeable sketch states, and only
        the keep-set columns (the packed feature matrix, the response)
        ever materialize full-length — peak host memory stops scaling
        with the intermediate featurization width.  ``chunk_rows=None``
        (default) keeps today's in-core path byte-identical.
        ``prefetch_chunks`` bounds the reader thread's parse-ahead depth
        (chunk k+1 parses while chunk k transforms).

        ``checkpoint_dir`` enables checkpoint/resume.  On the out-of-core
        path (with ``chunk_rows``): chunk-level — streaming-fit states +
        a chunks-consumed cursor persist atomically every
        ``checkpoint_every_chunks`` chunks, and re-running the same train
        against the same directory after a crash resumes from the last
        durable point instead of refitting (docs/robustness.md;
        workflow/checkpoint.py for what resumes where).  On the in-core
        path: sweep-level — the directory routes to every ModelSelector
        stage as a MID-SWEEP cursor (completed sweep units + halving rung
        state; docs/multichip.md resume semantics).  A checkpoint from a
        different reader/pipeline/chunk geometry (or a different sweep)
        raises ``CheckpointMismatchError`` rather than silently blending
        runs.

        ``tuner`` (a :class:`transmogrifai_tpu.tuning.Tuner`) opts THIS
        train into the adaptive machinery (docs/tuning.md): every
        ModelSelector stage runs under the tuner's sweep ``strategy``
        ("halving" = successive halving over the candidate grid; the
        stages' own settings are restored afterwards, the ``with_mesh``
        contract), and with ``auto_plan=True`` the cost planner picks
        stream-vs-in-core and the chunk geometry when ``chunk_rows`` is
        not given and the reader can estimate its rows.  ``tuner=None``
        (default) keeps today's paths byte-identical.

        Every train additionally appends its per-stage (rows, cols,
        dtype, backend, stage-kind, wall) observations to the shared cost
        history (``benchmarks/cost_history.json``; ``TMOG_COST_HISTORY``
        redirects or disables) — the learned cost model's training data.
        """
        from ..obs.trace import begin_span, end_span
        from ..utils.profiling import OpStep, with_job_group

        retain_mb = None
        if (tuner is not None and getattr(tuner, "auto_plan", False)
                and chunk_rows is None and self.reader is not None):
            advice = self._plan_advice(tuner)
            if advice is not None and advice.mode == "stream":
                chunk_rows = advice.chunk_rows
                prefetch_chunks = advice.prefetch_chunks
                retain_mb = advice.retain_mb
        tuned_stages = self._apply_tuner(tuner)
        from ..distributed.runtime import current_pod

        if current_pod().declared and chunk_rows is None:
            raise ValueError(
                "pod trains run out-of-core only — pass chunk_rows=k "
                "(the pod protocol is built on host-sharded chunk "
                "streams and mergeable fit states; docs/distributed.md)")
        root = begin_span("workflow.train", cat="workflow",
                          chunked=chunk_rows is not None,
                          chunk_rows=chunk_rows)
        try:
            if chunk_rows is not None:
                return self._train_chunked(
                    chunk_rows, prefetch_chunks, profile,
                    validate=validate, checkpoint_dir=checkpoint_dir,
                    checkpoint_every=checkpoint_every_chunks,
                    retain_mb=retain_mb)
            if checkpoint_dir is not None:
                # in-core path: the checkpointable unit is the SELECTOR
                # SWEEP — route the directory to every ModelSelector stage
                # as a mid-sweep cursor (completed SweepUnits + halving
                # rung state, workflow/checkpoint.SweepCheckpointManager),
                # so an 8-chip sweep killed mid-flight resumes at its
                # cursor.  Without a selector there is nothing durable to
                # cut at, and the historical error stands.
                from ..selector.model_selector import ModelSelector

                dag = compute_dag(self.result_features)
                sels = [s for s in dag.all_stages()
                        if isinstance(s, ModelSelector)]
                if not sels:
                    raise ValueError(
                        "checkpoint_dir requires the out-of-core path — "
                        "pass chunk_rows=k as well (the in-core fit only "
                        "checkpoints ModelSelector sweeps, and this DAG "
                        "has none)")
                prev = [(s, s.sweep_checkpoint_dir) for s in sels]
                for s in sels:
                    s.sweep_checkpoint_dir = checkpoint_dir
                try:
                    return self._train_in_core(profile, validate=validate)
                finally:
                    for s, d in prev:
                        s.sweep_checkpoint_dir = d
            return self._train_in_core(profile, validate=validate)
        finally:
            end_span(root)
            for s, prev_strategy, prev_halving in tuned_stages:
                s.strategy = prev_strategy
                s.halving = prev_halving

    def _plan_advice(self, tuner):
        """Cost-planner advice for an auto_plan train, or None when the
        reader cannot estimate its rows (nothing to decide from)."""
        rows = self.reader.estimate_rows()
        if not rows:
            return None
        from ..tuning.planner import advise_plan

        cols = max(len(self.raw_features()), 1)
        return advise_plan(rows, cols,
                           cost_model=tuner.resolved_cost_model(),
                           host_budget_bytes=tuner.host_budget_bytes)

    def _apply_tuner(self, tuner):
        """Set the tuner's sweep strategy on every ModelSelector stage for
        this train; returns (stage, previous strategy, previous halving)
        records for the caller's restore."""
        if tuner is None:
            return []
        from ..selector.model_selector import ModelSelector

        dag = compute_dag(self.result_features)
        tuned = []
        for s in dag.all_stages():
            if isinstance(s, ModelSelector):
                tuned.append((s, s.strategy, s.halving))
                s.strategy = tuner.strategy
                if tuner.halving is not None:
                    s.halving = tuner.halving
        return tuned

    def _train_in_core(self, profile: bool,
                       validate: bool = True) -> "OpWorkflowModel":
        from ..utils.profiling import OpStep, with_job_group

        with with_job_group(OpStep.DataReadingAndFiltering):
            data = self.generate_raw_data()
            filter_results = None
            if self._raw_feature_filter is not None:
                prev_mesh = self._raw_feature_filter.mesh
                if self.mesh is not None:
                    # numeric distribution passes run row-sharded (psum) —
                    # the executor-distributed profile of the reference
                    self._raw_feature_filter.with_mesh(self.mesh)
                try:
                    data, filter_results = (
                        self._raw_feature_filter.filter_raw_data(
                            data, self.raw_features()))
                finally:
                    self._raw_feature_filter.with_mesh(prev_mesh)
                self._apply_blocklist(filter_results.dropped_features)
        dag = compute_dag(self.result_features)
        self._validate_stages(dag)
        lint_snap = self._lint_dag(dag) if validate else None
        self._inject_params(dag)
        # hand the mesh to every mesh-capable stage for THIS train only —
        # stages are user-owned objects shared across workflows, so the
        # previous mesh (usually None) is restored afterwards
        meshed_stages = []
        if self.mesh is not None:
            for s in dag.all_stages():
                if hasattr(s, "with_mesh"):
                    meshed_stages.append((s, getattr(s, "mesh", None)))
                    s.with_mesh(self.mesh)
        try:
            model = self._train_inner(data, dag, filter_results,
                                      profile=profile)
        finally:
            for s, prev in meshed_stages:
                s.with_mesh(prev)
        model.lint_snapshot = lint_snap
        if model.train_profile is not None:
            model.train_profile.lint = lint_snap
        return model

    def _lint_dag(self, dag: StagesDAG):
        """The train(validate=True) gate: static DAG lint; errors raise
        PipelineLintError before any data moves, warnings come back as a
        LintSnapshot (with the lint's wall time, so the always-on cost
        stays auditable next to train wall)."""
        import time

        from ..analysis.diagnostics import PipelineLintError
        from ..analysis.linter import lint_dag
        from ..utils.profiling import LintSnapshot

        t0 = time.perf_counter()
        findings = lint_dag(dag, result_features=self.result_features,
                            reader=self.reader)
        wall = time.perf_counter() - t0
        if findings.errors:
            raise PipelineLintError(findings)
        return LintSnapshot.from_findings(findings, wall)

    def _train_chunked(self, chunk_rows: int, prefetch: int,
                       profile: bool,
                       validate: bool = True,
                       checkpoint_dir: Optional[str] = None,
                       checkpoint_every: int = 16,
                       retain_mb: Optional[float] = None
                       ) -> "OpWorkflowModel":
        """The out-of-core train: chunked ingestion + streaming two-pass
        fit + in-core tail (see workflow/streaming.py).

        RawFeatureFilter composes: its distribution pass runs CHUNKED
        over the train reader (and the scoring reader, when given) as a
        mergeable-monoid profile (filters/raw_feature_filter.py
        ``filter_streaming``) before the fit passes — drop decisions are
        identical to the in-core pass, dropped features never parse
        again, and dropped map keys are cleaned per chunk.

        Workflow-level CV composes: during-DAG estimators accumulate
        fold-tagged mergeable states (one per fold, assigned per global
        row id) and the fold validation runs on merged complement states
        between prefix and tail (workflow/streaming_cv.py) — every
        during-DAG estimator must support streaming fit.
        """
        import os as _os

        from ..utils.profiling import OpStep, PlanProfiler, with_job_group
        from .streaming import fit_dag_streaming

        if self.reader is None:
            raise RuntimeError("no reader set — call set_reader/set_input_data")

        rcfg = getattr(self.reader, "resilience", None)
        sink = (rcfg.sink() if (rcfg is not None and rcfg.quarantines)
                else None)
        q0 = (sink.count, sink.rows) if sink is not None else (0, 0)

        # -- pod context: this process is ONE MEMBER of a multi-process
        #    train (distributed/podstream.py) — host-sharded ingest,
        #    state merges at pass boundaries, coordinator-only durables
        from ..distributed.runtime import current_pod

        pod = current_pod()
        pod_ctx = None
        if pod.declared:
            from ..distributed.podstream import PodStreamContext

            pod_ctx = PodStreamContext(pod, self.reader,
                                       self.raw_features(), chunk_rows)

        # -- RawFeatureFilter: chunked distribution pass + per-chunk clean
        filter_results = None
        rff_stats = None
        chunk_filter = None
        if self._raw_feature_filter is not None:
            with with_job_group(OpStep.DataReadingAndFiltering):
                # pod_ctx mirrors pod.active — uniform across the pod
                if pod_ctx is not None:  # tmog: disable=TM071
                    # each process profiles its own host ranges; the
                    # monoid accumulators allgather-merge inside, so
                    # every process makes identical drop decisions
                    filter_results, rff_stats = (
                        self._raw_feature_filter.filter_streaming(
                            pod_ctx.local_reader(), self.raw_features(),
                            chunk_rows, pod=pod))
                else:
                    filter_results, rff_stats = (
                        self._raw_feature_filter.filter_streaming(
                            self.reader, self.raw_features(), chunk_rows))
            self._apply_blocklist(filter_results.dropped_features)
            chunk_filter = self._rff_chunk_filter(filter_results)

        dag = compute_dag(self.result_features)
        self._validate_stages(dag)
        lint_snap = self._lint_dag(dag) if validate else None
        self._inject_params(dag)

        cv_ctx = self._streaming_cv_context(dag)
        fingerprint_extra = (cv_ctx.fingerprint()
                             if cv_ctx is not None else None)

        # chunked trains checkpoint at TWO granularities under one
        # directory: the streaming manager owns the prefix passes, and
        # every ModelSelector in the (in-core) tail gets a mid-sweep
        # cursor under <dir>/sweep — a SIGKILL anywhere resumes at the
        # finest durable point
        sel_prev = []
        if checkpoint_dir is not None:
            from ..selector.model_selector import ModelSelector

            for s in dag.all_stages():
                if (isinstance(s, ModelSelector)
                        and s.sweep_checkpoint_dir is None):
                    sel_prev.append((s, s.sweep_checkpoint_dir))
                    s.sweep_checkpoint_dir = _os.path.join(
                        checkpoint_dir, "sweep")
        meshed_stages = []
        shard_cols = None
        if self.mesh is not None:
            for s in dag.all_stages():
                if hasattr(s, "with_mesh"):
                    meshed_stages.append((s, getattr(s, "mesh", None)))
                    s.with_mesh(self.mesh)
            from ..parallel.mesh import has_grid_axis

            if pod_ctx is not None:
                pass  # pod trains gather on host; no device hand-off yet
            elif has_grid_axis(self.mesh):
                # streaming→sharded hand-off: each ModelSelector's packed
                # feature matrix streams straight into per-shard device
                # buffers (parallel/ingest.py) — the (N, D) matrix never
                # materializes on one host before the sharded sweep
                from ..selector.model_selector import ModelSelector

                shard_cols = {s.features_feature.name
                              for s in dag.all_stages()
                              if isinstance(s, ModelSelector)}
        # a profiler always runs (its per-stage timings feed the learned
        # cost model's history); it lands on the model only when asked for
        profiler = PlanProfiler()
        try:
            with with_job_group(OpStep.FeatureEngineering):
                fitted, transformed, ingest, fit_states = fit_dag_streaming(
                    dag, self.reader, self.raw_features(), chunk_rows,
                    keep=self._train_keep_columns(),
                    fitted_substitutes=dict(self._model_stages),
                    profiler=profiler, prefetch=prefetch,
                    checkpoint_dir=checkpoint_dir,
                    checkpoint_every=checkpoint_every,
                    retain_mb=retain_mb,
                    shard_onto=None if pod_ctx is not None else self.mesh,
                    shard_columns=shard_cols,
                    fingerprint_extra=fingerprint_extra,
                    cv_ctx=cv_ctx, chunk_filter=chunk_filter,
                    pod_ctx=pod_ctx)
        finally:
            for s, prev in meshed_stages:
                s.with_mesh(prev)
            for s, prev in sel_prev:
                s.sweep_checkpoint_dir = prev
        model = OpWorkflowModel(
            result_features=self.result_features,
            stages=fitted,
            train_data=transformed,
        )
        model.reader = self.reader
        model.raw_feature_filter_results = filter_results
        model.train_profile = profiler if profile else None
        model.ingest_profile = ingest
        ingest.rff = rff_stats
        if sink is not None:
            # totals over EVERY pass of this train, the RFF distribution
            # pass included — the sidecar dedupes on (source, location),
            # so a row hit by all three passes still counts once
            ingest.quarantined_records = sink.count - q0[0]
            ingest.quarantined_rows = sink.rows - q0[1]
        model.fit_states = fit_states
        model.lint_snapshot = lint_snap
        profiler.lint = lint_snap
        from ..models.trees import clear_sweep_caches
        clear_sweep_caches()
        from ..tuning.costmodel import record_train_observations
        record_train_observations(profiler)
        return model

    def _rff_chunk_filter(self, filter_results):
        """Per-chunk cleaner applying the filter's already-made drop
        decisions (map-key removal; dropped features never parse again
        because the blocklist pruned them out of the raw feature set)."""
        if not filter_results.dropped_map_keys:
            return None
        rff = self._raw_feature_filter
        dropped = list(filter_results.dropped_features)
        keys = dict(filter_results.dropped_map_keys)
        return lambda ds: rff.clean_chunk(ds, dropped, keys)

    def _streaming_cv_context(self, dag: StagesDAG):
        """The fold-tagged CV context for a chunked train/refresh, or
        None when workflow CV is off (or the DAG has no CV cut).  Raises
        a precise error naming the offending stage when a during-DAG
        estimator cannot stream — the one genuinely unsupported
        combination left."""
        if not self._workflow_cv:
            return None
        from .streaming_cv import StreamingCVContext

        cut = cut_dag_cv(dag)
        if cut.selector is None or not cut.during.layers:
            return None
        for s in cut.during.all_stages():
            if (isinstance(s, Estimator) and s.uid not in self._model_stages
                    and not s.supports_streaming_fit):
                raise ValueError(
                    f"chunk_rows with workflow-level CV requires every "
                    f"fold-refit (during-DAG) estimator to support "
                    f"streaming fit; stage {s.uid} "
                    f"({type(s).__name__}) does not — fit it in-core or "
                    f"make its state a mergeable monoid "
                    f"(stages/base.py streaming-fit protocol)")
        return StreamingCVContext(cut.selector, cut.during,
                                  dict(self._model_stages))

    def refresh(self, model: "OpWorkflowModel", data=None,
                chunk_rows: int = 512, prefetch_chunks: int = 2,
                profile: bool = False,
                checkpoint_dir: Optional[str] = None,
                checkpoint_every_chunks: int = 16) -> "OpWorkflowModel":
        """Warm-start refresh: partial_fit ``model`` from NEW data only.

        Every ``supports_streaming_fit`` estimator whose exported fit
        state rides on ``model`` (``fit_states`` — chunked trains and
        refreshes record them) resumes from that state and merges the
        new chunks via the streaming-fit protocol, so the result matches
        a full streaming retrain over old+new within each stage's
        declared ``streaming_fit_tol`` (contract TM027) while reading
        only the refresh window.  Estimators without a state — or whose
        upstream feature GEOMETRY changed (vocab rotation, keep-decision
        flip; see workflow/refresh.py) — refit from the new data alone,
        and non-streamable tails refit in-core on the materialized
        window; the returned model's ``refresh_report`` says which path
        each estimator took.

        ``data`` defaults to this workflow's reader (point either at the
        new window).  ``checkpoint_dir`` reuses the streaming checkpoint
        manager with a refresh-scoped fingerprint: a SIGKILLed refresh
        resumes mid-pass, and a refresh checkpoint can never resume into
        a plain train or a refresh of a different base model.

        The refreshed model carries freshly merged ``fit_states`` —
        refreshes chain.  Deployment belongs behind the guarded swap
        (``serving.GuardedSwap``): a refresh is a CANDIDATE, not a
        rollout.
        """
        from ..obs.flight import record_event
        from ..obs.trace import begin_span, end_span
        from ..utils.profiling import OpStep, PlanProfiler, with_job_group
        from .refresh import RefreshContext
        from .streaming import fit_dag_streaming

        if data is not None:
            self.set_input_data(data)
        if self.reader is None:
            raise RuntimeError(
                "no refresh data — pass data= or set a reader")
        from ..distributed.runtime import current_pod

        if current_pod().declared:
            raise ValueError(
                "warm-start refresh does not yet compose with the pod "
                "runtime — run the refresh single-process "
                "(docs/distributed.md)")
        # RawFeatureFilter composes by REUSING the base model's recorded
        # drop decisions (re-profiling mid-refresh could change the DAG
        # geometry under the warm-started states — never silently);
        # workflow CV composes via the same fold-tagged context as a
        # chunked train (the re-selection runs on the refresh window).
        filter_results = None
        chunk_filter = None
        if self._raw_feature_filter is not None:
            filter_results = getattr(model, "raw_feature_filter_results",
                                     None)
            if filter_results is None:
                raise ValueError(
                    "refresh with RawFeatureFilter requires the base "
                    "model's recorded filter results "
                    "(model.raw_feature_filter_results — train with the "
                    "filter first); re-profiling inside a refresh would "
                    "change the feature geometry under the warm-started "
                    "states")
            self._apply_blocklist(filter_results.dropped_features)
            chunk_filter = self._rff_chunk_filter(filter_results)
        dag = compute_dag(self.result_features)
        self._validate_stages(dag)
        lint_snap = self._lint_dag(dag)
        self._inject_params(dag)
        cv_ctx = self._streaming_cv_context(dag)
        ctx = RefreshContext(model, dag)
        fingerprint_extra = ctx.base_digest()
        if cv_ctx is not None:
            fingerprint_extra = {**fingerprint_extra,
                                 **cv_ctx.fingerprint()}
        profiler = PlanProfiler()
        root = begin_span("workflow.refresh", cat="workflow",
                          chunk_rows=chunk_rows)
        record_event("refresh.start", chunk_rows=chunk_rows)
        try:
            with with_job_group(OpStep.FeatureEngineering):
                fitted, transformed, ingest, fit_states = fit_dag_streaming(
                    dag, self.reader, self.raw_features(), chunk_rows,
                    keep=self._train_keep_columns(),
                    profiler=profiler, prefetch=prefetch_chunks,
                    checkpoint_dir=checkpoint_dir,
                    checkpoint_every=checkpoint_every_chunks,
                    refresh_ctx=ctx, fingerprint_extra=fingerprint_extra,
                    cv_ctx=cv_ctx, chunk_filter=chunk_filter)
        finally:
            end_span(root)
        refreshed = OpWorkflowModel(
            result_features=self.result_features,
            stages=fitted,
            train_data=transformed,
        )
        refreshed.reader = self.reader
        refreshed.raw_feature_filter_results = filter_results
        refreshed.train_profile = profiler if profile else None
        refreshed.ingest_profile = ingest
        refreshed.fit_states = fit_states
        refreshed.refresh_report = ctx.report.to_json()
        refreshed.lint_snapshot = lint_snap
        from ..models.trees import clear_sweep_caches
        clear_sweep_caches()
        from ..tuning.costmodel import record_train_observations
        record_train_observations(profiler)
        return refreshed

    def _train_inner(self, data, dag, filter_results,
                     profile: bool = False) -> "OpWorkflowModel":
        from ..utils.profiling import OpStep, PlanProfiler, with_job_group

        # a profiler always runs (the per-stage wall/rows/cols/dtype
        # records feed the learned cost model's shared history,
        # tuning/costmodel.py); it lands on the model only when asked for
        profiler = PlanProfiler()
        substitutes = dict(self._model_stages)
        if self._workflow_cv:
            # OpWorkflow.fitStages CV path (OpWorkflow.scala:403-453):
            # fit the leakage-free prefix once, run fold-refitting validation
            # to pick the winner, then fit the full DAG (the selector skips
            # validation because its best_estimator is already set).
            cut = cut_dag_cv(dag)
            if cut.selector is not None and cut.during.layers:
                with with_job_group(OpStep.CrossValidation):
                    # no keep-set here: before_data must retain every column
                    # the during-DAG and selector read downstream
                    before_fitted, before_data, _ = fit_and_transform_dag(
                        cut.before, data, fitted_substitutes=substitutes)
                    cut.selector.find_best_estimator(before_data, cut.during)
                    substitutes.update(
                        {m.uid: m for m in before_fitted
                         if isinstance(m, Model)})
        with with_job_group(OpStep.FeatureEngineering):
            fitted, transformed, _ = fit_and_transform_dag(
                dag, data, fitted_substitutes=substitutes,
                keep=self._train_keep_columns(), profiler=profiler)
        model = OpWorkflowModel(
            result_features=self.result_features,
            stages=fitted,
            train_data=transformed,
        )
        model.reader = self.reader
        model.raw_feature_filter_results = filter_results
        model.train_profile = profiler if profile else None
        # drop the sweep's upload/binning memos: their device buffers are
        # only useful within one train and holding them pressures HBM on
        # subsequent trains (measured a 6x slowdown at 1M rows)
        from ..models.trees import clear_sweep_caches
        clear_sweep_caches()
        from ..tuning.costmodel import record_train_observations
        record_train_observations(profiler)
        return model

    def _validate_stages(self, dag: StagesDAG) -> None:
        """Distinct-uid + serializability checks (the reference fails fast
        at train time too — OpWorkflow.checkSerializable,
        OpWorkflow.scala:280-338)."""
        seen = set()
        for s in dag.all_stages():
            if s.uid in seen:
                raise ValueError(f"duplicate stage uid {s.uid}")
            seen.add(s.uid)
        if not self._allow_non_serializable:
            from .persistence import check_serializable

            check_serializable(dag.all_stages())

    def compute_data_up_to(self, feature: Feature,
                           data=None) -> ColumnarDataset:
        """Materialize features up to (and including) ``feature``
        (OpWorkflow.computeDataUpTo :491).  Estimators above are fit."""
        if data is not None:
            self.set_input_data(data)
        raw = self.generate_raw_data()
        dag = compute_dag([feature])
        _, transformed, _ = fit_and_transform_dag(dag, raw)
        return transformed

    def load_model(self, path: str) -> "OpWorkflowModel":
        from .persistence import load_workflow_model

        return load_workflow_model(path)


class OpWorkflowModel(_WorkflowCore):
    def __init__(self, result_features: Sequence[Feature],
                 stages: Sequence[PipelineStage],
                 train_data: Optional[ColumnarDataset] = None):
        super().__init__()
        self.result_features = list(result_features)
        self.stages = list(stages)
        self.train_data = train_data
        self.raw_feature_filter_results = None
        #: PlanProfiler from ``OpWorkflow.train(profile=True)`` else None
        self.train_profile = None
        #: IngestProfiler from ``OpWorkflow.train(chunk_rows=k)`` else None
        self.ingest_profile = None
        #: LintSnapshot from ``OpWorkflow.train(validate=True)`` else None
        self.lint_snapshot = None
        #: exported streaming fit states by estimator uid (the warm-start
        #: capital ``OpWorkflow.refresh`` resumes from) — populated by
        #: chunked trains and refreshes, persisted with the model
        self.fit_states: Optional[Dict[str, Any]] = None
        #: RefreshReport JSON when this model came from a refresh
        self.refresh_report: Optional[Dict[str, Any]] = None
        self._scoring_dag_memo: Optional[StagesDAG] = None

    def _scoring_dag(self) -> StagesDAG:
        # rebuild feature DAG over fitted stages (copyWithNewStages parity);
        # memoized: the stage list is fixed after construction, and callers
        # (score_function per call site, save, serving-registry hot-swaps)
        # would otherwise redo DAG construction per call
        if self._scoring_dag_memo is None:
            stage_map = {s.uid: s for s in self.stages}
            feats = [f.copy_with_new_stages(stage_map)
                     for f in self.result_features]
            self._scoring_dag_memo = compute_dag(feats)
        return self._scoring_dag_memo

    def invalidate_scoring_dag(self) -> None:
        """Drop the memoized scoring DAG (only needed if ``stages`` is
        mutated in place after construction)."""
        self._scoring_dag_memo = None

    def score(self, data=None,
              keep_raw_features: bool = False,
              keep_intermediate_features: bool = False) -> ColumnarDataset:
        """Batched scoring over the fitted transformer DAG
        (OpWorkflowModel.score :259 / applyTransformationsDAG)."""
        if data is not None:
            self.set_input_data(data)
        raw = self.generate_raw_data()
        # the memoized per-DAG execution plan prunes intermediates as soon
        # as their last consumer stage has run (transform() is COW — raw is
        # never mutated, so no defensive copy needed)
        plan_keep = None
        if not keep_intermediate_features:
            plan_keep = {f.name for f in self.result_features}
            plan_keep |= {f.name for f in self.raw_features()
                          if f.is_response}
            if keep_raw_features:
                plan_keep |= {f.name for f in self.raw_features()}
        scored = transform_dag(self._scoring_dag(), raw,
                               keep=sorted(plan_keep)
                               if plan_keep is not None else None)
        if keep_raw_features and keep_intermediate_features:
            return scored
        keep = [f.name for f in self.result_features if f.name in scored]
        if keep_raw_features:
            keep = [f.name for f in self.raw_features()] + keep
        # always keep the response(s) for evaluation
        responses = [f.name for f in self.raw_features() if f.is_response]
        keep = responses + [k for k in keep if k not in responses]
        return scored.select([k for k in keep if k in scored])

    def evaluate(self, evaluator: OpEvaluatorBase, data=None,
                 scored: Optional[ColumnarDataset] = None) -> Dict[str, float]:
        if scored is None:
            scored = self.score(data)
        label, pred = self._eval_columns(scored)
        evaluator.label_col = evaluator.label_col or label
        evaluator.prediction_col = evaluator.prediction_col or pred
        return evaluator.evaluate(scored)

    def score_and_evaluate(self, evaluator: OpEvaluatorBase, data=None):
        scored = self.score(data)
        return scored, self.evaluate(evaluator, scored=scored)

    def _eval_columns(self, scored: ColumnarDataset):
        from ..types.feature_types import Prediction

        label = next((f.name for f in self.raw_features() if f.is_response), None)
        pred = next(
            (f.name for f in self.result_features
             if issubclass(f.ftype, Prediction) and f.name in scored), None)
        if pred is None:
            pred = next(
                (n for n in scored.names()
                 if issubclass(scored[n].ftype, Prediction)), None)
        return label, pred

    # -- introspection ------------------------------------------------------

    def get_fitted_stage(self, uid_or_name: str) -> PipelineStage:
        for s in self.stages:
            if s.uid == uid_or_name or type(s).__name__ == uid_or_name:
                return s
        raise KeyError(uid_or_name)

    def summary(self) -> Dict[str, Any]:
        """Merged stage metadata (OpWorkflowModel.summary :187)."""
        out: Dict[str, Any] = {}
        for s in self.stages:
            if s.metadata:
                out[s.uid] = _jsonable(s.metadata)
        return out

    def summary_json(self) -> str:
        return json.dumps(self.summary(), indent=2, default=str)

    def summary_pretty(self) -> str:
        """Human-readable training summary (summaryPretty :221)."""
        from ..selector.model_selector import ModelSelectorSummary

        lines: List[str] = []
        for s in self.stages:
            summ = s.metadata.get("model_selector_summary")
            if summ:
                lines.append("Evaluated models:")
                for row in summ.get("validationResults", [])[:20]:
                    lines.append(
                        f"  {row['modelType']} {row['params']} -> "
                        f"{row['metricName']}={row['metricValue']:.4f}")
                lines.append(
                    f"Best model: {summ.get('bestModelType')} "
                    f"{summ.get('bestModelParams')}")
                hold = summ.get("holdoutMetrics")
                if hold:
                    lines.append("Holdout metrics: " + json.dumps(hold))
            sc = s.metadata.get("summary")
            if sc and "dropped" in sc:
                lines.append(
                    f"SanityChecker dropped {len(sc['dropped'])} columns: "
                    f"{sc['dropped'][:10]}")
        return "\n".join(lines) if lines else "(no fitted summaries)"

    def model_insights(self, feature: Optional[Feature] = None):
        from ..insights.model_insights import extract_model_insights

        return extract_model_insights(self, feature)

    def save(self, path: str, overwrite: bool = True) -> None:
        from .persistence import save_workflow_model

        save_workflow_model(self, path, overwrite=overwrite)

    @staticmethod
    def load(path: str) -> "OpWorkflowModel":
        from .persistence import load_workflow_model

        return load_workflow_model(path)


def _jsonable(obj):
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        if isinstance(obj, dict):
            return {k: _jsonable(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            return [_jsonable(v) for v in obj]
        if isinstance(obj, np.ndarray):
            return obj.tolist()
        return str(obj)
